#!/usr/bin/env bash
# Repo static gate, runnable outside pytest (CI wires this next to the
# tier-1 suite):
#
#   1. `python -m maelstrom_tpu.analyze` — trace the production
#      round_fn/scan_fn (plain + --mesh 1,2 on a forced 2-device CPU
#      mesh) AND the vmapped fleet scan/round variants (`--fleet`:
#      plain + --mesh 2,1, the cluster axis sharded over dp) and lint
#      the hot host modules; fails on any finding not in
#      analyze/baseline.json (doc/analyze.md).
#   2. `ruff check` — the generic-Python lint floor (pyproject.toml
#      [tool.ruff]); skipped with a notice when ruff isn't installed
#      (pip install -e .[dev]), since minimal images don't bake it in.
#
# Env knobs: ANALYZE_ARGS adds CLI flags (e.g. --programs lin-kv for a
# quick pass), JAX_PLATFORMS/XLA_FLAGS override the defaults below.

set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
# two virtual CPU devices so the --mesh variants are audited everywhere
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=2}"

echo "== static audit: python -m maelstrom_tpu.analyze =="
# shellcheck disable=SC2086
python -m maelstrom_tpu.analyze --format "${ANALYZE_FORMAT:-text}" \
    ${ANALYZE_ARGS:-}

# Jaxpr cost auditor (doc/analyze.md "cost model"): roofline records
# for the same production entry points on the same forced 2-device
# mesh, gated against analyze/cost_baseline.json — fails on
# collective-on-dp / carry-growth / hbm-overflow / intensity-regression
# findings. COST_AUDIT=0 skips (the hazard audit above stays the core).
if [ "${COST_AUDIT:-1}" = "1" ]; then
    echo "== cost audit: python -m maelstrom_tpu.analyze --cost =="
    # shellcheck disable=SC2086
    python -m maelstrom_tpu.analyze --cost \
        --format "${ANALYZE_FORMAT:-text}" ${COST_ARGS:-} > /dev/null
    echo "== cost audit clean =="
fi

if command -v ruff >/dev/null 2>&1; then
    echo "== ruff check =="
    ruff check .
else
    echo "== ruff not installed: skipping (pip install -e .[dev]) =="
fi

# Continuous-mode smoke (doc/streams.md): streaming kafka under the
# combined five-package soup — offered load injected INSIDE the
# compiled windows while faults are live, graded incrementally; the
# CLI exit code carries validity. STREAM_SMOKE=0 skips (the static
# audit above stays the gate's core).
if [ "${STREAM_SMOKE:-1}" = "1" ]; then
    echo "== continuous-mode stream smoke =="
    SMOKE_STORE="$(mktemp -d)"
    python -m maelstrom_tpu test -w kafka --node tpu:kafka \
        --node-count 5 --continuous --kafka-groups 2 \
        --rate 20 --time-limit 2 --seed 7 --no-audit \
        --nemesis kill,pause,partition,duplicate,weather \
        --nemesis-interval 0.7 --store "$SMOKE_STORE" > /dev/null
    rm -rf "$SMOKE_STORE"
    echo "== stream smoke valid =="
fi

# Fleet-continuous smoke (ISSUE 12, doc/perf.md "vectorized host
# driver"): `--fleet 2 --continuous` streaming kafka end to end,
# AUDITED (the fleet self-report traces the vmapped sched-inject scan
# this run actually dispatches), then the same fleet on the post-hoc
# path (--no-overlap) — each cluster's windowed-grader workload verdict
# must be bit-equal to its post-hoc verdict (windows/checker-lag
# accounting stripped). FLEET_STREAM_SMOKE=0 skips.
if [ "${FLEET_STREAM_SMOKE:-1}" = "1" ]; then
    echo "== fleet-continuous smoke =="
    SMOKE_STORE="$(mktemp -d)"
    python -m maelstrom_tpu test -w kafka --node tpu:kafka \
        --node-count 5 --continuous --kafka-groups 2 --fleet 2 \
        --rate 20 --time-limit 2 --seed 7 \
        --store "$SMOKE_STORE/win" > /dev/null
    python -m maelstrom_tpu test -w kafka --node tpu:kafka \
        --node-count 5 --continuous --kafka-groups 2 --fleet 2 \
        --rate 20 --time-limit 2 --seed 7 --no-overlap --no-audit \
        --store "$SMOKE_STORE/post" > /dev/null
    python - "$SMOKE_STORE" <<'PY'
import json, os, sys
root = sys.argv[1]
def wl(side, i):
    with open(os.path.join(root, side, "latest",
                           f"cluster-{i:04d}", "results.json")) as f:
        r = json.load(f)["workload"]
    return {k: v for k, v in r.items()
            if k not in ("windows", "checker-lag")}
for i in range(2):
    win, post = wl("win", i), wl("post", i)
    assert win == post, \
        f"cluster {i} windowed/post-hoc verdicts diverge:\n{win}\n{post}"
    assert win["valid"] is True, win
with open(os.path.join(root, "win", "latest", "results.json")) as f:
    res = json.load(f)
assert res["continuous"] is True and res["host-polls"] > 0, res
# columnar client sessions (ISSUE 17): the fleet defaults to the
# shared column table and reports the per-wave host wall
assert res["sessions"] == "columnar", res
assert res["host-wall-per-wave"] > 0, res
assert res["static-audit"]["ok"] is True, res["static-audit"]
print("fleet-continuous smoke: verdicts bit-equal, audited, valid")
PY
    rm -rf "$SMOKE_STORE"
    echo "== fleet-continuous smoke valid =="
fi

# fleet_stream bench smoke (ISSUE 17, doc/perf.md "columnar client
# sessions"): a tiny BENCH_MODE=fleet_stream sweep must record the
# host_wall_per_wave column on every point (the flatness/speedup
# evidence the committed r01 artifacts carry at full scale).
# FLEET_SESSIONS_SMOKE=0 skips.
if [ "${FLEET_SESSIONS_SMOKE:-1}" = "1" ]; then
    echo "== fleet_stream sessions smoke =="
    BENCH_MODE=fleet_stream BENCH_FLEET_STREAM_SIZES=1,2 \
        BENCH_FLEET_STREAM_MULTS=1 BENCH_FLEET_STREAM_TIME_LIMIT=1.0 \
        BENCH_FLEET_STREAM_COMPARE_MIN=2 \
        python bench.py > /tmp/fleet-sessions-smoke.json
    python - /tmp/fleet-sessions-smoke.json <<'PY'
import json, sys
rec = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
pts = rec["points"]
assert all(p["host_wall_per_wave"] is not None
           and p["host_wall_per_wave"] > 0 for p in pts), pts
modes = {(p["fleet"], p["sessions"]) for p in pts}
assert (2, "columnar") in modes and (2, "coroutine") in modes, modes
assert rec["session_speedup"], rec
print("fleet_stream sessions smoke: host_wall_per_wave recorded "
      "for", sorted(modes))
PY
    rm -f /tmp/fleet-sessions-smoke.json
    echo "== fleet_stream sessions smoke valid =="
fi

# Batched-broadcast smoke (ISSUE 9, doc/perf.md): the distilled-batch
# node end to end — plain, sharded (--mesh 1,2 over the forced 2-device
# CPU mesh), and under the combined nemesis soup — expansion proofs
# verified and the set-full verdict graded on every path. The batcher
# step fns themselves are traced by the static audit above (the
# broadcast-batched entry in analyze's program set). BATCHED_SMOKE=0
# skips.
if [ "${BATCHED_SMOKE:-1}" = "1" ]; then
    echo "== batched-broadcast smoke =="
    SMOKE_STORE="$(mktemp -d)"
    python -m maelstrom_tpu test -w broadcast-batched \
        --node tpu:broadcast-batched --node-count 5 --rate 20 \
        --time-limit 2 --seed 7 --no-audit \
        --store "$SMOKE_STORE" > /dev/null
    python -m maelstrom_tpu test -w broadcast-batched \
        --node tpu:broadcast-batched --node-count 5 --rate 20 \
        --time-limit 2 --seed 7 --mesh 1,2 --no-audit \
        --store "$SMOKE_STORE" > /dev/null
    python -m maelstrom_tpu test -w broadcast-batched \
        --node tpu:broadcast-batched --node-count 5 --rate 20 \
        --time-limit 3 --seed 11 --no-audit \
        --nemesis kill,pause,partition,duplicate \
        --nemesis-interval 0.7 --store "$SMOKE_STORE" > /dev/null
    rm -rf "$SMOKE_STORE"
    echo "== batched-broadcast smoke valid =="
fi

# Compartmentalized-consensus smoke (ISSUE 10, doc/compartment.md):
# lin-kv on the role-partitioned proxy/acceptor/replica cluster —
# plain, sharded (--mesh 1,2 over the forced 2-device CPU mesh), and a
# role-targeted kill+partition soup that kills a proxy and cuts an
# acceptor column, verdict valid post-heal. The compartment and
# services step fns are traced by the static audit above (the
# `compartment` / `lin-tso` entries in analyze's program set).
# COMPARTMENT_SMOKE=0 skips.
if [ "${COMPARTMENT_SMOKE:-1}" = "1" ]; then
    echo "== compartmentalized-consensus smoke =="
    SMOKE_STORE="$(mktemp -d)"
    python -m maelstrom_tpu test -w lin-kv --node tpu:compartment \
        --roles proxies=2,acceptors=2x2,replicas=2 --rate 20 \
        --time-limit 2 --seed 7 --no-audit \
        --store "$SMOKE_STORE" > /dev/null
    python -m maelstrom_tpu test -w lin-kv --node tpu:compartment \
        --roles proxies=2,acceptors=2x2,replicas=2 --rate 20 \
        --time-limit 2 --seed 7 --mesh 1,2 --no-audit \
        --store "$SMOKE_STORE" > /dev/null
    python -m maelstrom_tpu test -w lin-kv --node tpu:compartment \
        --roles proxies=2,acceptors=2x2,replicas=2 --rate 20 \
        --time-limit 3 --seed 11 --no-audit \
        --nemesis kill,partition --nemesis-interval 0.7 \
        --nemesis-targets kill=proxies,partition=acceptor-col-0 \
        --store "$SMOKE_STORE" > /dev/null
    rm -rf "$SMOKE_STORE"
    echo "== compartment smoke valid =="
fi

# Device-checker smoke (ISSUE 11, doc/perf.md "device-resident
# grading"): one txn-list-append run with the device-resident elle
# checker on the forced 2-device CPU mesh, AUDITED (the self-report
# traces this run's own step fns next to the elle kernels the gate
# above already covered), then the same seed on the host checker path
# — the workload verdict blocks must match exactly (the device block
# and windowed-grading accounting stripped). DEVICE_CHECKER_SMOKE=0
# skips.
if [ "${DEVICE_CHECKER_SMOKE:-1}" = "1" ]; then
    echo "== device-checker smoke =="
    SMOKE_STORE="$(mktemp -d)"
    python -m maelstrom_tpu test -w txn-list-append \
        --node tpu:txn-list-append --node-count 5 --rate 20 \
        --time-limit 2 --seed 7 --mesh 1,2 --device-checker on \
        --store "$SMOKE_STORE/dev" > /dev/null
    python -m maelstrom_tpu test -w txn-list-append \
        --node tpu:txn-list-append --node-count 5 --rate 20 \
        --time-limit 2 --seed 7 --mesh 1,2 --device-checker off \
        --no-audit --store "$SMOKE_STORE/host" > /dev/null
    python - "$SMOKE_STORE" <<'PY'
import json, os, sys
root = sys.argv[1]
def wl(side):
    with open(os.path.join(root, side, "latest", "results.json")) as f:
        r = json.load(f)["workload"]
    return {k: v for k, v in r.items()
            if k not in ("device", "windows", "checker-lag")}
dev, host = wl("dev"), wl("host")
assert dev == host, f"device/host elle verdicts diverge:\n{dev}\n{host}"
assert dev["valid"] is True, dev
print("device-checker smoke: verdicts bit-equal, valid")
PY
    rm -rf "$SMOKE_STORE"
    echo "== device-checker smoke valid =="
fi

# Flight-recorder smoke (ISSUE 13, doc/observability.md): one AUDITED
# run with --telemetry — the self-report traces this run's ring-enabled
# step fns (zero new findings required), the Chrome trace must load as
# JSON with the phase taxonomy, every telemetry.jsonl record must be
# schema-valid, and the final record's quantiles must equal the
# post-hoc PerfChecker block. TELEMETRY_SMOKE=0 skips.
if [ "${TELEMETRY_SMOKE:-1}" = "1" ]; then
    echo "== flight-recorder telemetry smoke =="
    SMOKE_STORE="$(mktemp -d)"
    python -m maelstrom_tpu test -w lin-kv --node tpu:lin-kv \
        --node-count 5 --rate 20 --time-limit 2 --seed 7 \
        --telemetry "$SMOKE_STORE/tel" \
        --store "$SMOKE_STORE" > /dev/null
    python - "$SMOKE_STORE" <<'PY'
import json, os, sys
from maelstrom_tpu.telemetry import validate_record
root = sys.argv[1]
with open(os.path.join(root, "latest", "results.json")) as f:
    res = json.load(f)
assert res["valid"] is True, res.get("valid")
assert res["net"]["static-audit"]["ok"] is True, res["net"]["static-audit"]
ring = res["net"]["telemetry"]
assert ring["sent"] == res["net"]["all"]["send-count"], ring
with open(os.path.join(root, "tel", "trace.json")) as f:
    trace = json.load(f)
names = {e["name"] for e in trace["traceEvents"]}
assert {"schedule-encode", "dispatch", "device-get"} <= names, names
recs = [json.loads(line)
        for line in open(os.path.join(root, "tel", "telemetry.jsonl"))]
assert recs, "no telemetry records"
for rec in recs:
    problems = validate_record(rec)
    assert not problems, (rec, problems)
final = [r for r in recs if r["type"] == "final"][-1]
perf = {k: v for k, v in res["perf"]["latency-ms"].items()
        if k != "by-f"}
assert final["lat_ms"] == perf, (final["lat_ms"], perf)
print("telemetry smoke: audited, trace loads, jsonl schema-valid, "
      "windowed == post-hoc")
PY
    rm -rf "$SMOKE_STORE"
    echo "== telemetry smoke valid =="
fi

# Leader-failover smoke (ISSUE 14, doc/compartment.md "leader
# election"): one AUDITED `--nemesis-targets kill=sequencer` run under
# the combined kill/pause/partition/duplicate soup on the 3-candidate
# elected compartment — must complete >= 1 failover, grade
# linearizable, carry the availability block (bounded dips), and pass
# the static audit with the election step fns traced at zero new
# findings. FAILOVER_SMOKE=0 skips.
if [ "${FAILOVER_SMOKE:-1}" = "1" ]; then
    echo "== leader-failover smoke =="
    SMOKE_STORE="$(mktemp -d)"
    python -m maelstrom_tpu test -w lin-kv --node tpu:compartment \
        --roles sequencers=3,proxies=2,acceptors=2x2,replicas=2 \
        --rate 30 --time-limit 4 --seed 11 --timeout-ms 400 \
        --nemesis kill,pause,partition,duplicate \
        --nemesis-interval 0.8 --nemesis-targets kill=sequencer \
        --store "$SMOKE_STORE" > /dev/null
    python - "$SMOKE_STORE" <<'PY'
import json, os, sys
root = sys.argv[1]
with open(os.path.join(root, "latest", "results.json")) as f:
    res = json.load(f)
assert res["valid"] is True, res.get("valid")
assert res["workload"]["valid"] is True, res["workload"]
audit = res["net"]["static-audit"]
assert audit["ok"] is True, audit
avail = res["availability"]
assert avail["election"]["failovers"] >= 1, avail["election"]
assert avail["longest-ok-gap-rounds"] < avail["final-round"], avail
assert "failover-recovery-rounds" in avail, avail
print(f"failover smoke: {avail['election']['failovers']} failovers, "
      f"longest dip {avail['longest-ok-gap-rounds']} rounds, "
      f"linearizable, audited")
PY
    rm -rf "$SMOKE_STORE"
    echo "== failover smoke valid =="
fi

# Ordering-layer smoke (ISSUE 15, doc/ordering.md): one NEW
# (engine x applier) combination — txn-list-append over batched atomic
# broadcast — driven through the CLI's --ordering axis under a fault
# soup, graded by the stock Elle checker, static-audit block ok.
# ORDERING_SMOKE=0 skips.
if [ "${ORDERING_SMOKE:-1}" = "1" ]; then
    echo "== ordering-layer smoke =="
    SMOKE_STORE="$(mktemp -d)"
    python -m maelstrom_tpu test -w txn-list-append --ordering batched \
        --node-count 5 --rate 20 --time-limit 3 --seed 11 \
        --nemesis kill,partition,duplicate --nemesis-interval 0.8 \
        --store "$SMOKE_STORE" > /dev/null
    python - "$SMOKE_STORE" <<'PY'
import json, os, sys
root = sys.argv[1]
with open(os.path.join(root, "latest", "results.json")) as f:
    res = json.load(f)
assert res["valid"] is True, res.get("valid")
assert res["workload"]["valid"] is True, res["workload"]
audit = res["net"]["static-audit"]
assert audit["ok"] is True, audit
print("ordering smoke: txn-list-append over batched broadcast under "
      "kill/partition/duplicate — Elle-valid, audited")
PY
    rm -rf "$SMOKE_STORE"
    echo "== ordering smoke valid =="
fi

# Byzantine-conviction smoke (ISSUE 16, doc/faults.md "byzantine is a
# conviction driver"): one AUDITED run with the equivocating-sequencer
# adversary live on the elected compartment — the `byzantine` results
# block must CONVICT (>= 1 conviction naming a rule and a culprit,
# every injected corruption accounted for, none spurious), and the
# static audit must trace the byz-enabled step fns at zero new
# findings. BYZANTINE_SMOKE=0 skips.
if [ "${BYZANTINE_SMOKE:-1}" = "1" ]; then
    echo "== byzantine-conviction smoke =="
    SMOKE_STORE="$(mktemp -d)"
    python -m maelstrom_tpu test -w lin-kv --node tpu:compartment \
        --roles sequencers=2,proxies=2,acceptors=1x2,replicas=1 \
        --rate 20 --time-limit 4 --seed 3 --compartment-retry 3 \
        --nemesis byzantine --nemesis-targets byzantine=sequencers \
        --byz-attacks equivocation --nemesis-interval 0.8 \
        --store "$SMOKE_STORE" > /dev/null || true
    python - "$SMOKE_STORE" <<'PY'
import json, os, sys
root = sys.argv[1]
with open(os.path.join(root, "latest", "results.json")) as f:
    res = json.load(f)
blk = res["byzantine"]
assert blk["valid"] is True, blk
convs = blk["convictions"]
assert convs, "adversary ran but nobody was convicted"
for c in convs:
    assert c["rule"] and c["culprit"], c
assert not blk["unconvicted"], blk["unconvicted"]
assert not blk["spurious"], blk["spurious"]
audit = res["net"]["static-audit"]
assert audit["ok"] is True, audit
inj = {k: v for k, v in blk["injected"].items() if v}
print(f"byzantine smoke: injected {inj}, convicted "
      + ", ".join(f"{c['rule']}={c['culprit']}" for c in convs)
      + ", audited")
PY
    rm -rf "$SMOKE_STORE"
    echo "== byzantine smoke valid =="
fi

# Pod-scale mixed-mesh smoke (ISSUE 18, doc/perf.md "pod-scale mixed
# mesh"): an AUDITED `--fleet 2 --mesh 2,2` run — the dp>1 x sp>1
# shape PR 2 had to reject, now running the scan body manual under
# shard_map — on a FORCED 4-device CPU mesh, under the combined
# kill/pause/partition/duplicate soup. The fleet self-report must
# trace the shard_map-wrapped fns at zero new findings
# (replicated-scatter armed by the 2x2 pins), and every cluster's
# history and workload verdict must be bit-equal to its own standalone
# run of the same seed. MIXEDMESH_SMOKE=0 skips.
if [ "${MIXEDMESH_SMOKE:-1}" = "1" ]; then
    echo "== pod-scale mixed-mesh smoke =="
    SMOKE_STORE="$(mktemp -d)"
    MIXEDMESH_XLA="--xla_force_host_platform_device_count=4"
    XLA_FLAGS="$MIXEDMESH_XLA" python -m maelstrom_tpu test \
        -w broadcast --node tpu:broadcast --topology grid \
        --node-count 5 --rate 10 --time-limit 2 --seed 7 \
        --fleet 2 --mesh 2,2 \
        --nemesis kill,pause,partition,duplicate \
        --nemesis-interval 0.4 --store "$SMOKE_STORE/fleet" > /dev/null
    for seed in 7 8; do
        XLA_FLAGS="$MIXEDMESH_XLA" python -m maelstrom_tpu test \
            -w broadcast --node tpu:broadcast --topology grid \
            --node-count 5 --rate 10 --time-limit 2 --seed "$seed" \
            --nemesis kill,pause,partition,duplicate \
            --nemesis-interval 0.4 --no-audit \
            --store "$SMOKE_STORE/solo$seed" > /dev/null
    done
    python - "$SMOKE_STORE" <<'PY'
import json, os, sys
root = sys.argv[1]
with open(os.path.join(root, "fleet", "latest", "results.json")) as f:
    res = json.load(f)
assert res["fleet"] == 2 and res["mesh"] == "2,2", res
assert res["valid"] is True, res.get("valid")
assert res["static-audit"]["ok"] is True, res["static-audit"]
def wl(path):
    with open(os.path.join(path, "results.json")) as f:
        r = json.load(f)["workload"]
    return {k: v for k, v in r.items()
            if k not in ("windows", "checker-lag", "check-wall-s")}
for i, seed in enumerate((7, 8)):
    cdir = os.path.join(root, "fleet", "latest", f"cluster-{i:04d}")
    sdir = os.path.join(root, f"solo{seed}", "latest")
    with open(os.path.join(cdir, "history.jsonl"), "rb") as f:
        ch = f.read()
    with open(os.path.join(sdir, "history.jsonl"), "rb") as f:
        sh = f.read()
    assert ch == sh, f"cluster {i} history diverges from seed {seed}"
    assert wl(cdir) == wl(sdir), \
        f"cluster {i} verdict diverges from seed {seed}"
print("mixed-mesh smoke: --fleet 2 --mesh 2,2 audited, per-cluster "
      "histories + verdicts bit-equal to standalone")
PY
    rm -rf "$SMOKE_STORE"
    echo "== mixed-mesh smoke valid =="
fi

echo "== static gate clean =="
