#!/usr/bin/env python3
"""Echo server demo node (counterpart of demo/ruby/echo.rb)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node

node = Node()


@node.on("echo")
def echo(msg):
    node.reply(msg, {"type": "echo_ok", "echo": msg["body"]["echo"]})


if __name__ == "__main__":
    node.run()
