#!/usr/bin/env python3
"""PN-counter demo node: a pair of G-counters (increments/decrements) with
periodic gossip merge (counterpart of demo/ruby/pn_counter.rb)."""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node

node = Node()
lock = threading.Lock()
inc = {}    # node_id -> sum of positive deltas observed locally
dec = {}    # node_id -> sum of negative magnitude


def merge(mine, theirs):
    for k, v in theirs.items():
        mine[k] = max(mine.get(k, 0), v)


@node.on("add")
def add(msg):
    delta = msg["body"]["delta"]
    with lock:
        if delta >= 0:
            inc[node.node_id] = inc.get(node.node_id, 0) + delta
        else:
            dec[node.node_id] = dec.get(node.node_id, 0) - delta
    node.reply(msg, {"type": "add_ok"})


@node.on("read")
def read(msg):
    with lock:
        value = sum(inc.values()) - sum(dec.values())
    node.reply(msg, {"type": "read_ok", "value": value})


@node.on("replicate")
def replicate(msg):
    with lock:
        merge(inc, msg["body"]["inc"])
        merge(dec, msg["body"]["dec"])


@node.every(0.7)
def gossip():
    with lock:
        body = {"type": "replicate", "inc": dict(inc), "dec": dict(dec)}
    for other in node.node_ids:
        if other != node.node_id:
            node.send_msg(other, body)


if __name__ == "__main__":
    node.run()
