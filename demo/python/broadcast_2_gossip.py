#!/usr/bin/env python3
"""Broadcast tutorial, stage 2 (doc/tutorial/03-broadcast.md): on first
receipt, forward the value once to every neighbor except whoever sent
it (deg-1 fan-out — the skip-sender rule the reference's naive node
uses). Converges on a healthy network and passes the checker there; a
single lost or partition-blocked hop loses the value FOREVER, and the
checker exhibits it under `--nemesis partition`. Fire-once is fast and
wrong; stage 3 adds the retry loop that makes it merely fast."""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()
lock = threading.Lock()
messages = set()
neighbors = []


@node.on("topology")
def topology(msg):
    global neighbors
    with lock:
        neighbors = msg["body"]["topology"].get(node.node_id, [])
    node.reply(msg, {"type": "topology_ok"})


@node.on("broadcast")
def broadcast(msg):
    v = msg["body"]["message"]
    new = False
    with lock:
        if v not in messages:
            messages.add(v)
            new = True
        nbs = list(neighbors)
    if new:
        for n in nbs:
            if n != msg["src"]:
                node.send_msg(n, {"type": "broadcast", "message": v})
    if msg["body"].get("msg_id") is not None:
        node.reply(msg, {"type": "broadcast_ok"})


@node.on("read")
def read(msg):
    with lock:
        vals = sorted(messages)
    node.reply(msg, {"type": "read_ok", "messages": vals})


if __name__ == "__main__":
    node.run()
