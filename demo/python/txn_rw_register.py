#!/usr/bin/env python3
"""Transactional read/write registers behind one lin-kv register (the
txn-rw-register workload): the whole key space is a JSON map under a
single linearizable root, transactions apply functionally to a copy,
and a compare-and-set commits — the same shared-state transactor shape
as demo/python/datomic_shared_state.py, with register semantics. A
lost CAS race aborts with error 30 (txn-conflict, definite)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

node = Node()
ROOT = "root"


def apply_txn(db: dict, txn: list):
    db = dict(db)
    out = []
    for f, k, v in txn:
        key = str(k)
        if f == "r":
            out.append([f, k, db.get(key)])
        elif f == "w":
            db[key] = v
            out.append([f, k, v])
        else:
            raise RPCError.not_supported(f"unknown micro-op {f!r}")
    return db, out


@node.on("txn")
def handle_txn(msg):
    txn = msg["body"]["txn"]
    try:
        cur = node.sync_rpc("lin-kv", {"type": "read", "key": ROOT})
        db = cur["value"] or {}
    except RPCError as e:
        if e.code != 20:
            raise
        db = {}
    db2, completed = apply_txn(db, txn)
    try:
        node.sync_rpc("lin-kv", {"type": "cas", "key": ROOT,
                                 "from": db, "to": db2,
                                 "create_if_not_exists": True})
    except RPCError as e:
        if e.code in (20, 22):
            raise RPCError.txn_conflict(
                "CAS of the database root failed; txn aborted")
        raise
    node.reply(msg, {"type": "txn_ok", "txn": completed})


if __name__ == "__main__":
    node.run()
