#!/usr/bin/env python3
"""Raft tutorial, stage 2 (doc/tutorial/06-raft.md): stage 1's KV plus
leader election — roles, terms, randomized timeouts, vote counting, and
heartbeats that suppress elections. No log yet: the leader answers
clients from its *local* dict; everyone else returns error 11
(temporarily-unavailable) so the workload retries elsewhere.

With a stable leader this is accidentally linearizable (one dict serves
everything). Kill the stability — `--nemesis partition` — and a new
leader is elected with an *empty* dict: acknowledged writes vanish, and
the checker shows the exact stale read. Election gives you a single
writer; it does not give you durability. That's stage 3's job."""

import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

# overridable so slow/oversubscribed CI hosts can widen the stability
# margin (heartbeat gaps from scheduler hiccups trigger elections)
ELECTION_S = float(os.environ.get("RAFT_ELECTION_S", "0.6"))
HEARTBEAT_S = float(os.environ.get("RAFT_HEARTBEAT_S", "0.08"))

node = Node()
lock = threading.RLock()

role = "follower"
term = 0
voted_for = None
votes = set()
leader = None
deadline = 0.0
kv = {}


def reset_deadline():
    # randomized: with a fixed timeout, candidates collide forever
    global deadline
    deadline = time.monotonic() + ELECTION_S * (1 + random.random())


def other_nodes():
    return [p for p in node.node_ids if p != node.node_id]


def majority():
    return len(node.node_ids) // 2 + 1


def become_follower(new_term):
    global role, term, voted_for, leader
    role, term, voted_for, leader = "follower", new_term, None, None
    reset_deadline()


def become_candidate():
    global role, term, voted_for, votes, leader
    role = "candidate"
    term += 1
    voted_for = node.node_id
    votes = {node.node_id}
    leader = None
    reset_deadline()
    node.log(f"became candidate for term {term}")
    for peer in other_nodes():
        node.rpc(peer, {"type": "request_vote", "term": term,
                        "candidate_id": node.node_id},
                 callback=on_vote_reply(term))


def become_leader():
    global role, leader
    role, leader = "leader", node.node_id
    node.log(f"became leader for term {term}")


def on_vote_reply(req_term):
    def cb(msg):
        with lock:
            b = msg["body"]
            if b.get("term", 0) > term:
                become_follower(b["term"])
            elif (role == "candidate" and term == req_term
                  and b.get("vote_granted")):
                votes.add(msg["src"])
                if len(votes) >= majority():
                    become_leader()
    return cb


@node.on("request_vote")
def handle_request_vote(msg):
    global voted_for
    with lock:
        b = msg["body"]
        if b["term"] > term:
            become_follower(b["term"])
        granted = (b["term"] == term
                   and voted_for in (None, b["candidate_id"]))
        if granted:
            voted_for = b["candidate_id"]
            reset_deadline()
        node.reply(msg, {"type": "request_vote_res", "term": term,
                         "vote_granted": granted})


@node.on("append_entries")          # heartbeat only, no entries yet
def handle_heartbeat(msg):
    global role, leader
    with lock:
        b = msg["body"]
        if b["term"] > term:
            become_follower(b["term"])
        if b["term"] == term:
            if role == "candidate":
                role = "follower"
            leader = b["leader_id"]
            reset_deadline()
        node.reply(msg, {"type": "append_entries_res", "term": term})


def handle_client(msg):
    with lock:
        if role != "leader":
            raise RPCError.temporarily_unavailable(
                f"not the leader (ask {leader})")
        b = msg["body"]
        t, k = b["type"], b.get("key")
        if t == "read":
            if k not in kv:
                raise RPCError.key_does_not_exist(f"no key {k}")
            node.reply(msg, {"type": "read_ok", "value": kv[k]})
        elif t == "write":
            kv[k] = b["value"]
            node.reply(msg, {"type": "write_ok"})
        elif t == "cas":
            if k not in kv:
                raise RPCError.key_does_not_exist(f"no key {k}")
            if kv[k] != b["from"]:
                raise RPCError.precondition_failed(
                    f"expected {b['from']!r}, had {kv[k]!r}")
            kv[k] = b["to"]
            node.reply(msg, {"type": "cas_ok"})


for _type in ("read", "write", "cas"):
    node.on(_type)(handle_client)


@node.every(HEARTBEAT_S)
def tick():
    with lock:
        if role == "leader":
            for peer in other_nodes():
                node.rpc(peer, {"type": "append_entries", "term": term,
                                "leader_id": node.node_id})
        elif time.monotonic() >= deadline:
            become_candidate()


reset_deadline()

if __name__ == "__main__":
    node.run()
