#!/usr/bin/env python3
"""Raft tutorial, stage 1 (doc/tutorial/06-raft.md): a key-value store
with no replication at all — one dict, three RPCs, correct error codes.

Linearizable at --node-count 1 (one node IS a total order); demonstrably
NOT at --node-count 5, where every node holds its own dict and the
checker exhibits a read that observes a stale register. The rest of the
chapter is the work of making five dicts behave like this one."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

node = Node()
kv = {}


@node.on("read")
def read(msg):
    k = msg["body"]["key"]
    if k not in kv:
        raise RPCError.key_does_not_exist(f"no key {k}")
    node.reply(msg, {"type": "read_ok", "value": kv[k]})


@node.on("write")
def write(msg):
    kv[msg["body"]["key"]] = msg["body"]["value"]
    node.reply(msg, {"type": "write_ok"})


@node.on("cas")
def cas(msg):
    b = msg["body"]
    k = b["key"]
    if k not in kv:
        raise RPCError.key_does_not_exist(f"no key {k}")
    if kv[k] != b["from"]:
        raise RPCError.precondition_failed(
            f"expected {b['from']!r}, had {kv[k]!r}")
    kv[k] = b["to"]
    node.reply(msg, {"type": "cas_ok"})


if __name__ == "__main__":
    node.run()
