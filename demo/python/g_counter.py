#!/usr/bin/env python3
"""Grow-only counter node on the generic CRDT server (counterpart of
demo/clojure/gcounter.clj; g-counter workload, non-negative deltas)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from crdt import CRDTServer, GCounter
from node import Node

node = Node()
server = CRDTServer(node, GCounter(), interval_s=0.7)


@node.on("add")
def add(msg):
    with server.lock:
        server.value = server.value.add(node.node_id, msg["body"]["delta"])
    node.reply(msg, {"type": "add_ok"})


if __name__ == "__main__":
    node.run()
