#!/usr/bin/env python3
"""Raft tutorial, stage 3 (doc/tutorial/06-raft.md): stage 2 plus a
replicated log — the leader appends client ops, ships them in
append_entries with (prev_index, prev_term) consistency checks, walks
next_idx back on mismatch, truncates conflicting suffixes, and grants
votes only to candidates with an up-to-date log.

Deliberately missing: the majority-commit barrier. The leader applies
an entry and ACKS THE CLIENT the moment it appends locally. So state
survives leader changes (the new leader's log carries the old writes —
run it and watch), but an isolated old leader still acknowledges writes
that the majority never saw; when it rejoins, its unreplicated suffix
is truncated and those acknowledged writes vanish. The checker
exhibits exactly that under `--nemesis partition`. Durable != agreed:
that's stage 4's commit index."""

import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

# overridable so slow/oversubscribed CI hosts can widen the stability
# margin (heartbeat gaps from scheduler hiccups trigger elections)
ELECTION_S = float(os.environ.get("RAFT_ELECTION_S", "0.6"))
HEARTBEAT_S = float(os.environ.get("RAFT_HEARTBEAT_S", "0.08"))

node = Node()
lock = threading.RLock()

role = "follower"
term = 0
voted_for = None
votes = set()
log = []                # entries: {"term": t, "op": body}
applied_idx = -1
next_idx = {}
match_idx = {}
leader = None
deadline = 0.0
kv = {}


def reset_deadline():
    global deadline
    deadline = time.monotonic() + ELECTION_S * (1 + random.random())


def other_nodes():
    return [p for p in node.node_ids if p != node.node_id]


def majority():
    return len(node.node_ids) // 2 + 1


def last_log():
    return (len(log) - 1, log[-1]["term"]) if log else (-1, 0)


def become_follower(new_term):
    global role, term, voted_for, leader
    role, term, voted_for, leader = "follower", new_term, None, None
    reset_deadline()


def become_candidate():
    global role, term, voted_for, votes, leader
    role = "candidate"
    term += 1
    voted_for = node.node_id
    votes = {node.node_id}
    leader = None
    reset_deadline()
    node.log(f"became candidate for term {term}")
    li, lt = last_log()
    for peer in other_nodes():
        node.rpc(peer, {"type": "request_vote", "term": term,
                        "candidate_id": node.node_id,
                        "last_log_index": li, "last_log_term": lt},
                 callback=on_vote_reply(term))


def become_leader():
    global role, leader, next_idx, match_idx
    role, leader = "leader", node.node_id
    next_idx = {p: len(log) for p in other_nodes()}
    match_idx = {p: -1 for p in other_nodes()}
    node.log(f"became leader for term {term} (log={len(log)})")
    replicate()


def on_vote_reply(req_term):
    def cb(msg):
        with lock:
            b = msg["body"]
            if b.get("term", 0) > term:
                become_follower(b["term"])
            elif (role == "candidate" and term == req_term
                  and b.get("vote_granted")):
                votes.add(msg["src"])
                if len(votes) >= majority():
                    become_leader()
    return cb


@node.on("request_vote")
def handle_request_vote(msg):
    global voted_for
    with lock:
        b = msg["body"]
        if b["term"] > term:
            become_follower(b["term"])
        granted = False
        if b["term"] == term and voted_for in (None, b["candidate_id"]):
            # the up-to-date restriction (last term, then last index):
            # a stale log must not win an election and overwrite others
            li, lt = last_log()
            if (b["last_log_term"], b["last_log_index"]) >= (lt, li):
                granted = True
                voted_for = b["candidate_id"]
                reset_deadline()
        node.reply(msg, {"type": "request_vote_res", "term": term,
                         "vote_granted": granted})


@node.on("append_entries")
def handle_append_entries(msg):
    global role, leader
    with lock:
        b = msg["body"]
        if b["term"] > term:
            become_follower(b["term"])
        if b["term"] < term:
            node.reply(msg, {"type": "append_entries_res", "term": term,
                             "success": False, "match_index": -1})
            return
        if role == "candidate":
            role = "follower"
        leader = b["leader_id"]
        reset_deadline()
        prev = b["prev_log_index"]
        if prev >= 0 and (prev >= len(log)
                          or log[prev]["term"] != b["prev_log_term"]):
            node.reply(msg, {"type": "append_entries_res", "term": term,
                             "success": False,
                             "match_index": min(len(log) - 1, prev - 1)})
            return
        global applied_idx
        i = prev + 1
        for ent in b["entries"]:
            if i < len(log) and log[i]["term"] != ent["term"]:
                del log[i:]                     # conflict: truncate suffix
                # the dict keeps the truncated entries' effects — stage 3
                # cannot undo an apply; the checker will exhibit this
                applied_idx = min(applied_idx, i - 1)
            if i >= len(log):
                log.append(ent)
            i += 1
        apply_all()                             # stage 3: apply = append
        node.reply(msg, {"type": "append_entries_res", "term": term,
                         "success": True,
                         "match_index": prev + len(b["entries"])})


def on_append_reply(peer, req_term):
    def cb(msg):
        with lock:
            b = msg["body"]
            if b.get("term", 0) > term:
                become_follower(b["term"])
                return
            if role != "leader" or term != req_term:
                return
            if b.get("success"):
                match_idx[peer] = max(match_idx[peer], b["match_index"])
                next_idx[peer] = match_idx[peer] + 1
            else:
                next_idx[peer] = max(0, min(next_idx[peer] - 1,
                                            b.get("match_index", -1) + 1))
    return cb


def replicate():
    for peer in other_nodes():
        nx = next_idx[peer]
        prev = nx - 1
        prev_term = log[prev]["term"] if prev >= 0 else 0
        node.rpc(peer, {"type": "append_entries", "term": term,
                        "leader_id": node.node_id,
                        "prev_log_index": prev, "prev_log_term": prev_term,
                        "entries": log[nx:nx + 16]},
                 callback=on_append_reply(peer, term))


def apply_op(body):
    t, k = body["type"], body.get("key")
    if t == "read":
        if k not in kv:
            return RPCError.key_does_not_exist(f"no key {k}").to_body()
        return {"type": "read_ok", "value": kv[k]}
    if t == "write":
        kv[k] = body["value"]
        return {"type": "write_ok"}
    if t == "cas":
        if k not in kv:
            return RPCError.key_does_not_exist(f"no key {k}").to_body()
        if kv[k] != body["from"]:
            return RPCError.precondition_failed(
                f"expected {body['from']!r}, had {kv[k]!r}").to_body()
        kv[k] = body["to"]
        return {"type": "cas_ok"}


def apply_all():
    """Stage 3's deliberate hole: every appended entry applies at once —
    no commit index, no majority barrier."""
    global applied_idx
    while applied_idx < len(log) - 1:
        applied_idx += 1
        if log[applied_idx].get("op") is not None:
            apply_op(log[applied_idx]["op"])


def handle_client(msg):
    global applied_idx
    with lock:
        if role != "leader":
            raise RPCError.temporarily_unavailable(
                f"not the leader (ask {leader})")
        log.append({"term": term, "op": msg["body"]})
        reply = apply_op(msg["body"])   # ack at append: NOT safe
        applied_idx = len(log) - 1
        node.log(f"acked index {applied_idx} before replication")
        node.reply(msg, reply)
        replicate()


for _type in ("read", "write", "cas"):
    node.on(_type)(handle_client)


@node.every(HEARTBEAT_S)
def tick():
    with lock:
        if role == "leader":
            replicate()
        elif time.monotonic() >= deadline:
            become_candidate()


reset_deadline()

if __name__ == "__main__":
    node.run()
