#!/usr/bin/env python3
"""Broadcast demo node with neighbor gossip and retry until acknowledged,
so values survive partitions (counterpart of demo/ruby/broadcast.rb)."""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node

node = Node()
lock = threading.Lock()
messages = set()
neighbors = []
unacked = {}        # neighbor -> set of values not yet acknowledged

# BCAST_STAMP=1: log the monotonic instant this node first held each
# value (ack-stamp lag measurement, maelstrom_tpu.parity_ackstamp)
STAMP = bool(os.environ.get("BCAST_STAMP"))


@node.on("topology")
def topology(msg):
    global neighbors
    with lock:
        neighbors = msg["body"]["topology"].get(node.node_id, [])
        for n in neighbors:
            unacked.setdefault(n, set())
    node.log(f"My neighbors are {neighbors}")
    node.reply(msg, {"type": "topology_ok"})


def accept(value, sender=None):
    with lock:
        if value in messages:
            return
        messages.add(value)
        for n in neighbors:
            if n != sender:
                unacked[n].add(value)
    if STAMP:
        node.log(f"HADVAL {value} {time.monotonic_ns()}")


@node.on("broadcast")
def broadcast(msg):
    accept(msg["body"]["message"], sender=msg["src"])
    if msg["body"].get("msg_id") is not None:
        node.reply(msg, {"type": "broadcast_ok"})


@node.on("read")
def read(msg):
    with lock:
        vals = sorted(messages)
    node.reply(msg, {"type": "read_ok", "messages": vals})


@node.every(0.5)
def retry():
    """Re-send unacknowledged values to neighbors until they ack."""
    with lock:
        pending = [(n, v) for n, vs in unacked.items() for v in vs]
    for n, v in pending:
        def on_ack(reply, n=n, v=v):
            with lock:
                unacked.get(n, set()).discard(v)
        node.rpc(n, {"type": "broadcast", "message": v}, on_ack)


if __name__ == "__main__":
    node.run()
