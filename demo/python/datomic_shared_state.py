#!/usr/bin/env python3
"""A transactional list-append server backed by the lin-kv service, in the
style of the reference's Datomic demo (`demo/ruby/datomic_list_append.rb`):
the whole database lives behind a single linearizable register, transactions
apply functionally to a copy, and a compare-and-set commits — a CAS race
returns error 30 (txn-conflict, definite), which the checker understands as
an aborted transaction.

Because every transaction serializes through one lin-kv CAS, the system is
strict-serializable by construction (reference
`doc/05-datomic/01-single-node.md` onward)."""

import sys
import os

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

node = Node()
ROOT = "root"


def apply_txn(db: dict, txn: list):
    """Functionally applies micro-ops to db; returns (db', completed)."""
    db = dict(db)
    out = []
    for f, k, v in txn:
        key = str(k)
        if f == "r":
            out.append([f, k, db.get(key)])
        elif f == "append":
            db[key] = list(db.get(key) or []) + [v]
            out.append([f, k, v])
        else:
            raise RPCError.not_supported(f"unknown micro-op {f!r}")
    return db, out


@node.on("txn")
def handle_txn(msg):
    txn = msg["body"]["txn"]
    try:
        cur = node.sync_rpc("lin-kv", {"type": "read", "key": ROOT})
        db = cur["value"] or {}
    except RPCError as e:
        if e.code != 20:
            raise
        db = {}
    db2, completed = apply_txn(db, txn)
    try:
        node.sync_rpc("lin-kv", {"type": "cas", "key": ROOT,
                                 "from": db, "to": db2,
                                 "create_if_not_exists": True})
    except RPCError as e:
        if e.code in (20, 22):
            raise RPCError.txn_conflict(
                "CAS of the database root failed; txn aborted")
        raise
    node.reply(msg, {"type": "txn_ok", "txn": completed})


if __name__ == "__main__":
    node.run()
