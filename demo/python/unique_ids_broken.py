#!/usr/bin/env python3
"""Unique-id tutorial's deliberately broken stage
(doc/tutorial/09-workloads.md): ids are wall-clock milliseconds — the
classic "timestamps are probably unique" mistake. Two requests inside
one millisecond (or any two nodes asked in the same one) collide, and
the unique-ids checker names every collision."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()


@node.on("generate")
def generate(msg):
    node.reply(msg, {"type": "generate_ok",
                     "id": int(time.time() * 1000)})


if __name__ == "__main__":
    node.run()
