#!/usr/bin/env python3
"""A lin-kv server that simply proxies every operation to Maelstrom's
built-in `lin-kv` service — the smallest possible way to pass the lin-kv
workload (reference `demo/ruby/lin_kv_proxy.rb`): the service is
linearizable, so the proxy is too."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

node = Node()


def proxy(msg, body):
    try:
        res = node.sync_rpc("lin-kv", body)
    except RPCError as e:
        node.reply(msg, e.to_body())
        return
    node.reply(msg, res)


@node.on("read")
def read(msg):
    proxy(msg, {"type": "read", "key": msg["body"]["key"]})


@node.on("write")
def write(msg):
    res_body = {"type": "write", "key": msg["body"]["key"],
                "value": msg["body"]["value"]}
    proxy(msg, res_body)


@node.on("cas")
def cas(msg):
    b = msg["body"]
    proxy(msg, {"type": "cas", "key": b["key"], "from": b["from"],
                "to": b["to"]})


if __name__ == "__main__":
    node.run()
