#!/usr/bin/env python3
"""A userland Raft serving lin-kv, written against this repo's tiny node
library — the host-path counterpart of the reference's Raft demos
(`demo/ruby/raft.rb`, `demo/python/raft.py` in the reference tree; this is
a fresh implementation, not a port).

Leader election with randomized timeouts, log replication with conflict
truncation, majority commit, and a KV state machine applied in log order.
Client requests at a non-leader return error 11 (temporarily-unavailable,
definite -> the workload records a clean :fail and retries elsewhere),
like the reference demo. Reads go through the log, so every operation
linearizes at its apply point.

Handlers run on separate threads (node.run's dispatch), so all Raft state
is guarded by one big lock; timers are periodic tasks."""

import os
import random
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

# overridable so slow/oversubscribed CI hosts can widen the stability
# margin (heartbeat gaps from scheduler hiccups trigger elections)
ELECTION_S = float(os.environ.get("RAFT_ELECTION_S", "0.6"))
HEARTBEAT_S = float(os.environ.get("RAFT_HEARTBEAT_S", "0.08"))

node = Node()
lock = threading.RLock()

role = "follower"
term = 0
voted_for = None
votes = set()
log = []                # entries: {"term": t, "op": body-or-None}
commit_idx = -1
applied_idx = -1
next_idx = {}
match_idx = {}
kv = {}
leader = None
deadline = 0.0


def now():
    import time
    return time.monotonic()


def reset_deadline():
    global deadline
    deadline = now() + ELECTION_S * (1 + random.random())


def last_log():
    if log:
        return len(log) - 1, log[-1]["term"]
    return -1, 0


def become_follower(new_term):
    global role, term, voted_for, leader
    role = "follower"
    term = new_term
    voted_for = None
    leader = None
    reset_deadline()


def become_candidate():
    global role, term, voted_for, votes, leader
    role = "candidate"
    term += 1
    voted_for = node.node_id
    votes = {node.node_id}
    leader = None
    reset_deadline()
    node.log(f"became candidate for term {term}")
    li, lt = last_log()
    for peer in other_nodes():
        node.rpc(peer, {"type": "request_vote", "term": term,
                        "candidate_id": node.node_id,
                        "last_log_index": li, "last_log_term": lt},
                 callback=on_vote_reply(term))


def become_leader():
    global role, leader, next_idx, match_idx
    role = "leader"
    leader = node.node_id
    next_idx = {p: len(log) for p in other_nodes()}
    match_idx = {p: -1 for p in other_nodes()}
    node.log(f"became leader for term {term}")
    replicate()


def other_nodes():
    return [p for p in node.node_ids if p != node.node_id]


def majority():
    return len(node.node_ids) // 2 + 1


def on_vote_reply(req_term):
    def cb(msg):
        global votes
        with lock:
            b = msg["body"]
            if b.get("term", 0) > term:
                become_follower(b["term"])
                return
            if role != "candidate" or term != req_term:
                return
            if b.get("vote_granted"):
                votes.add(msg["src"])
                if len(votes) >= majority():
                    become_leader()
    return cb


@node.on("request_vote")
def handle_request_vote(msg):
    global voted_for
    with lock:
        b = msg["body"]
        if b["term"] > term:
            become_follower(b["term"])
        granted = False
        if b["term"] == term and voted_for in (None, b["candidate_id"]):
            li, lt = last_log()
            up_to_date = (b["last_log_term"], b["last_log_index"]) >= (lt,
                                                                       li)
            if up_to_date:
                granted = True
                voted_for = b["candidate_id"]
                reset_deadline()
        node.reply(msg, {"type": "request_vote_res", "term": term,
                         "vote_granted": granted})


@node.on("append_entries")
def handle_append_entries(msg):
    global log, commit_idx, leader
    with lock:
        b = msg["body"]
        if b["term"] > term:
            become_follower(b["term"])
        if b["term"] < term:
            node.reply(msg, {"type": "append_entries_res", "term": term,
                             "success": False, "match_index": -1})
            return
        # valid leader for our term
        global role
        if role == "candidate":
            role = "follower"
        leader = b["leader_id"]
        reset_deadline()
        prev = b["prev_log_index"]
        if prev >= 0 and (prev >= len(log)
                          or log[prev]["term"] != b["prev_log_term"]):
            node.reply(msg, {"type": "append_entries_res", "term": term,
                             "success": False,
                             "match_index": min(len(log) - 1, prev - 1)})
            return
        i = prev + 1
        for ent in b["entries"]:
            if i < len(log) and log[i]["term"] != ent["term"]:
                del log[i:]                     # conflict: truncate suffix
            if i >= len(log):
                log.append(ent)
            i += 1
        new_match = prev + len(b["entries"])
        global commit_idx
        commit_idx = max(commit_idx, min(b["leader_commit"], new_match))
        apply_committed()
        node.reply(msg, {"type": "append_entries_res", "term": term,
                         "success": True, "match_index": new_match})


def on_append_reply(peer, req_term):
    def cb(msg):
        global commit_idx
        with lock:
            b = msg["body"]
            if b.get("term", 0) > term:
                become_follower(b["term"])
                return
            if role != "leader" or term != req_term:
                return
            if b.get("success"):
                match_idx[peer] = max(match_idx[peer], b["match_index"])
                next_idx[peer] = match_idx[peer] + 1
                # commit = majority-replicated index with a current-term
                # entry (paper section 5.4.2)
                marks = sorted(list(match_idx.values()) + [len(log) - 1],
                               reverse=True)
                best = marks[majority() - 1]
                if best > commit_idx and best >= 0 \
                        and log[best]["term"] == term:
                    commit_idx = best
                    apply_committed()
            else:
                next_idx[peer] = max(0, min(next_idx[peer] - 1,
                                            b.get("match_index", -1) + 1))
    return cb


def replicate():
    with lock:
        if role != "leader":
            return
        for peer in other_nodes():
            nx = next_idx[peer]
            prev = nx - 1
            prev_term = log[prev]["term"] if prev >= 0 else 0
            entries = log[nx:nx + 16]
            node.rpc(peer, {"type": "append_entries", "term": term,
                            "leader_id": node.node_id,
                            "prev_log_index": prev,
                            "prev_log_term": prev_term,
                            "entries": entries,
                            "leader_commit": commit_idx},
                     callback=on_append_reply(peer, term))


def apply_committed():
    """Applies entries up to commit_idx; the leader answers clients."""
    global applied_idx
    while applied_idx < commit_idx:
        applied_idx += 1
        ent = log[applied_idx]
        op = ent.get("op")
        if op is None:
            continue
        body, client = op["body"], op["client"]
        t, k = body["type"], body.get("key")
        reply = None
        if t == "read":
            if k in kv:
                reply = {"type": "read_ok", "value": kv[k]}
            else:
                reply = RPCError.key_does_not_exist(f"no key {k}").to_body()
        elif t == "write":
            kv[k] = body["value"]
            reply = {"type": "write_ok"}
        elif t == "cas":
            if k not in kv:
                reply = RPCError.key_does_not_exist(f"no key {k}").to_body()
            elif kv[k] != body["from"]:
                reply = RPCError.precondition_failed(
                    f"expected {body['from']!r}, had {kv[k]!r}").to_body()
            else:
                kv[k] = body["to"]
                reply = {"type": "cas_ok"}
        if role == "leader" and client is not None:
            node.send_msg(client, dict(reply,
                                       in_reply_to=op["msg_id"]))


def handle_client(msg):
    with lock:
        if role == "leader":
            log.append({"term": term,
                        "op": {"body": msg["body"], "client": msg["src"],
                               "msg_id": msg["body"]["msg_id"]}})
            replicate()
            return
        target = leader
    if target is None or target == node.node_id:
        raise RPCError.temporarily_unavailable("no leader known yet")

    # forward to the known leader and relay its reply back to the client
    def relay(res):
        body = {k: v for k, v in res["body"].items()
                if k not in ("msg_id", "in_reply_to")}
        body["in_reply_to"] = msg["body"]["msg_id"]
        node.send_msg(msg["src"], body)

    fwd = {k: v for k, v in msg["body"].items() if k != "msg_id"}
    node.rpc(target, fwd, callback=relay)


for _type in ("read", "write", "cas"):
    node.on(_type)(handle_client)


@node.every(HEARTBEAT_S)
def tick():
    with lock:
        if role == "leader":
            replicate()
        elif now() >= deadline:
            become_candidate()


reset_deadline()

if __name__ == "__main__":
    node.run()
