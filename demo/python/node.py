"""A tiny node library for writing Maelstrom-protocol nodes in Python.

The userland counterpart of the reference's per-language node libraries
(demo/ruby/node.rb): handler registration, replies, async RPCs with
callbacks, synchronous RPCs, and periodic tasks — speaking newline-delimited
JSON on stdin/stdout and logging to stderr (doc/protocol.md).
"""

from __future__ import annotations

import json
import sys
import threading
import time


class RPCError(Exception):
    def __init__(self, code: int, text: str):
        self.code = code
        self.text = text
        super().__init__(text)

    def to_body(self) -> dict:
        return {"type": "error", "code": self.code, "text": self.text}

    @classmethod
    def timeout(cls, text):
        return cls(0, text)

    @classmethod
    def not_supported(cls, text):
        return cls(10, text)

    @classmethod
    def temporarily_unavailable(cls, text):
        return cls(11, text)

    @classmethod
    def abort(cls, text):
        return cls(14, text)

    @classmethod
    def key_does_not_exist(cls, text):
        return cls(20, text)

    @classmethod
    def precondition_failed(cls, text):
        return cls(22, text)

    @classmethod
    def txn_conflict(cls, text):
        return cls(30, text)


class Node:
    def __init__(self):
        self.node_id = None
        self.node_ids = []
        self.next_msg_id = 0
        self.handlers = {}
        self.callbacks = {}
        self.periodic = []          # (interval_s, fn)
        self.lock = threading.RLock()
        self.log_lock = threading.Lock()

        @self.on("init")
        def handle_init(msg):
            self.node_id = msg["body"]["node_id"]
            self.node_ids = msg["body"]["node_ids"]
            self.log(f"Node {self.node_id} initialized")
            self.reply(msg, {"type": "init_ok"})
            for interval, fn in self.periodic:
                t = threading.Thread(target=self._every, args=(interval, fn),
                                     daemon=True)
                t.start()

    # --- registration ---

    def on(self, type: str):
        def register(fn):
            if type in self.handlers:
                raise KeyError(f"already a handler for {type}")
            self.handlers[type] = fn
            return fn
        return register

    def every(self, interval_s: float):
        def register(fn):
            self.periodic.append((interval_s, fn))
            return fn
        return register

    def _every(self, interval_s, fn):
        while True:
            time.sleep(interval_s)
            try:
                fn()
            except Exception as e:
                self.log(f"periodic task error: {e!r}")

    # --- I/O ---

    def log(self, text: str):
        with self.log_lock:
            print(text, file=sys.stderr, flush=True)

    def send_msg(self, dest: str, body: dict):
        msg = {"src": self.node_id, "dest": dest, "body": body}
        with self.lock:
            print(json.dumps(msg), flush=True)

    def reply(self, request: dict, body: dict):
        body = dict(body, in_reply_to=request["body"]["msg_id"])
        self.send_msg(request["src"], body)

    def rpc(self, dest: str, body: dict, callback=None):
        """Fire an RPC; callback(msg) runs on the reply."""
        with self.lock:
            self.next_msg_id += 1
            msg_id = self.next_msg_id
            if callback is not None:
                self.callbacks[msg_id] = callback
        self.send_msg(dest, dict(body, msg_id=msg_id))
        return msg_id

    def sync_rpc(self, dest: str, body: dict, timeout_s: float = 5.0) -> dict:
        """Blocking RPC; raises RPCError on error replies or timeout."""
        done = threading.Event()
        box = {}

        def cb(msg):
            box["msg"] = msg
            done.set()
        self.rpc(dest, body, cb)
        if not done.wait(timeout_s):
            raise RPCError.timeout(f"RPC to {dest} timed out")
        rbody = box["msg"]["body"]
        if rbody.get("type") == "error":
            raise RPCError(rbody.get("code", 13), rbody.get("text", ""))
        return rbody

    # --- main loop ---

    def handle(self, msg: dict):
        body = msg.get("body", {})
        reply_to = body.get("in_reply_to")
        if reply_to is not None:
            with self.lock:
                cb = self.callbacks.pop(reply_to, None)
            if cb:
                cb(msg)
            return
        handler = self.handlers.get(body.get("type"))
        if handler is None:
            if body.get("msg_id") is not None:
                self.reply(msg, RPCError.not_supported(
                    f"don't know how to handle {body.get('type')!r}"
                ).to_body())
            return
        try:
            handler(msg)
        except RPCError as e:
            self.reply(msg, e.to_body())
        except Exception as e:
            self.log(f"handler error: {e!r}")
            self.reply(msg, RPCError(13, repr(e)).to_body())

    def run(self, threaded: bool = True):
        """Reads messages from stdin forever. With threaded=True each
        message is handled on its own thread (like the ruby node lib), so
        sync RPCs inside handlers don't deadlock the loop."""
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            msg = json.loads(line)
            if threaded:
                threading.Thread(target=self.handle, args=(msg,),
                                 daemon=True).start()
            else:
                self.handle(msg)
