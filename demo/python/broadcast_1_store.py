#!/usr/bin/env python3
"""Broadcast tutorial, stage 1 (doc/tutorial/03-broadcast.md): accept
and acknowledge values, serve reads — and tell nobody. Passes trivially
at --node-count 1; at 5 nodes the stock checker fails the run, naming
each value that reached one node and was never seen by a read at
another. The chapter is the work of emptying that list."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()
messages = set()


@node.on("topology")
def topology(msg):
    node.reply(msg, {"type": "topology_ok"})


@node.on("broadcast")
def broadcast(msg):
    messages.add(msg["body"]["message"])
    node.reply(msg, {"type": "broadcast_ok"})


@node.on("read")
def read(msg):
    node.reply(msg, {"type": "read_ok", "messages": sorted(messages)})


if __name__ == "__main__":
    node.run()
