#!/usr/bin/env python3
"""Kafka-style replicated log over the built-in services (the `kafka`
workload): per-key logs live in `lin-kv` as JSON lists appended by a
CAS loop (offset = length before the append — the CAS makes the
assignment exclusive, so offsets never diverge), committed offsets in
`lin-kv` advanced by a monotone CAS (a stale commit never regresses
the mark). Polls read the whole list: full-prefix observation, which
is exactly what the checker's lost-write rule leans on."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

node = Node()


def kv_read(key, default):
    try:
        return node.sync_rpc("lin-kv", {"type": "read", "key": key})["value"]
    except RPCError as e:
        if e.code != 20:
            raise
        return default


@node.on("send")
def send(msg):
    b = msg["body"]
    key = f"log-{b['key']}"
    while True:
        cur = kv_read(key, [])
        try:
            node.sync_rpc("lin-kv", {"type": "cas", "key": key,
                                     "from": cur, "to": cur + [b["msg"]],
                                     "create_if_not_exists": True})
        except RPCError as e:
            if e.code in (20, 22):
                continue              # lost the race: re-read, retry
            raise
        node.reply(msg, {"type": "send_ok", "offset": len(cur)})
        return


@node.on("poll")
def poll(msg):
    out = {}
    for k in msg["body"]["keys"]:
        log = kv_read(f"log-{k}", [])
        if log:
            out[str(k)] = [[i, m] for i, m in enumerate(log)]
    node.reply(msg, {"type": "poll_ok", "msgs": out})


@node.on("commit_offsets")
def commit_offsets(msg):
    for k, o in msg["body"]["offsets"].items():
        key = f"commit-{k}"
        while True:
            cur = kv_read(key, -1)
            if cur >= o:
                break                 # a later commit already landed
            try:
                node.sync_rpc("lin-kv", {"type": "cas", "key": key,
                                         "from": cur, "to": o,
                                         "create_if_not_exists": True})
                break
            except RPCError as e:
                if e.code in (20, 22):
                    continue
                raise
    node.reply(msg, {"type": "commit_offsets_ok"})


@node.on("list_committed_offsets")
def list_committed(msg):
    out = {}
    for k in msg["body"]["keys"]:
        o = kv_read(f"commit-{k}", None)
        if o is not None:
            out[str(k)] = o
    node.reply(msg, {"type": "list_committed_offsets_ok", "offsets": out})


if __name__ == "__main__":
    node.run()
