#!/usr/bin/env python3
"""A strict-serializable transactional list-append store built on the
Datomic transactor model, over the built-in services (counterpart of the
reference's `demo/ruby/datomic_list_append.rb`):

  - the database is a map of key -> *thunk id*; thunks are immutable
    lists stored in the eventually-consistent `lww-kv` service (safe
    because a thunk, once written, never changes — last-write-wins
    can't disagree about a value that's only written once);
  - the root map itself lives behind a single well-known key in the
    linearizable `lin-kv` service, advanced by compare-and-set — every
    transaction serializes through that one CAS, which is what makes
    the whole store strict-serializable;
  - thunk ids must be globally unique: each takes a sequence number
    from the `seq-kv` service (a CAS-bumped counter — sequential
    consistency suffices for uniqueness) combined with this node's id,
    amortized by claiming blocks of ids at a time;
  - immutable thunks are cached forever after first read or write,
    which is the reference's "caching thunks" optimization
    (`doc/05-datomic/04-optimization.md`): it removes ~3 messages per
    transaction.

A CAS race aborts the transaction with error 30 (txn-conflict,
definite); the checker treats it as a clean abort."""

from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

node = Node()

ROOT = "root"            # well-known key in lin-kv
VALUE_SVC = "lww-kv"     # immutable thunk storage
SEQ_SVC = "seq-kv"       # unique-id sequence
ID_BLOCK = 32            # ids claimed per seq-kv round trip


class Ids:
    """Globally-unique thunk ids: blocks claimed from a seq-kv counter,
    suffixed with the node id for readability/debugging."""

    def __init__(self):
        self.next = 0
        self.limit = 0
        self.lock = threading.Lock()   # handlers run threaded

    def fresh(self) -> str:
        with self.lock:
            return self._fresh()

    def _fresh(self) -> str:
        if self.next >= self.limit:
            while True:
                try:
                    cur = node.sync_rpc(SEQ_SVC, {"type": "read",
                                                  "key": "thunk-seq"})
                    base = cur["value"]
                except RPCError as e:
                    if e.code != 20:
                        raise
                    base = 0
                try:
                    node.sync_rpc(SEQ_SVC, {
                        "type": "cas", "key": "thunk-seq",
                        "from": base, "to": base + ID_BLOCK,
                        "create_if_not_exists": True})
                except RPCError as e:
                    if e.code in (20, 22):
                        continue         # raced another claimant; retry
                    raise
                self.next, self.limit = base, base + ID_BLOCK
                break
        i = self.next
        self.next += 1
        return f"{i}-{node.node_id}"


ids = Ids()
thunk_cache: dict[str, list] = {}      # immutable: cache forever


def thunk_read(ptr: str) -> list:
    """Loads an immutable thunk, retrying while lww-kv replicas catch up
    (a thunk referenced by the root has been written somewhere; eventual
    consistency only delays visibility)."""
    got = thunk_cache.get(ptr)
    if got is not None:
        return got
    while True:
        try:
            value = node.sync_rpc(VALUE_SVC,
                                  {"type": "read", "key": ptr})["value"]
            thunk_cache[ptr] = value
            return value
        except RPCError as e:
            if e.code != 20:
                raise
            time.sleep(0.01)


def thunk_write(ptr: str, value: list):
    node.sync_rpc(VALUE_SVC, {"type": "write", "key": ptr, "value": value})
    thunk_cache[ptr] = value


@node.on("txn")
def handle_txn(msg):
    txn = msg["body"]["txn"]

    # load the current root (key -> thunk id)
    try:
        root = node.sync_rpc("lin-kv", {"type": "read", "key": ROOT})
        root = root["value"] or {}
    except RPCError as e:
        if e.code != 20:
            raise
        root = {}

    # apply micro-ops functionally: reads load thunks, appends create
    # fresh ones (written before the root moves, so no reader can ever
    # follow a dangling pointer)
    root2 = dict(root)
    completed = []
    for f, k, v in txn:
        key = str(k)
        if f == "r":
            ptr = root2.get(key)
            completed.append([f, k, list(thunk_read(ptr)) if ptr else None])
        elif f == "append":
            cur = thunk_read(root2[key]) if key in root2 else []
            ptr = ids.fresh()
            thunk_write(ptr, list(cur) + [v])
            root2[key] = ptr
            completed.append([f, k, v])
        else:
            raise RPCError.not_supported(f"unknown micro-op {f!r}")

    # commit: advance the root pointer map iff nobody else did
    if root2 != root:
        try:
            node.sync_rpc("lin-kv", {"type": "cas", "key": ROOT,
                                     "from": root, "to": root2,
                                     "create_if_not_exists": True})
        except RPCError as e:
            if e.code in (20, 22):
                raise RPCError.txn_conflict(
                    "CAS of the database root failed; txn aborted")
            raise
    node.reply(msg, {"type": "txn_ok", "txn": completed})


if __name__ == "__main__":
    node.run()
