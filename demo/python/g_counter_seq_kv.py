#!/usr/bin/env python3
"""A grow-only counter on the `seq-kv` service (counterpart of the
reference's `demo/clojure/gcounter.clj`, its only seq-kv client).

The whole counter lives in one seq-kv key, advanced by a CAS loop.
Sequential consistency means reads can be stale — a node may observe an
old total — but that's exactly what the g-counter/pn-counter checker
tolerates: every final read must land in the interval of defensible
sums, and a monotone counter behind by in-flight adds still does.
What seq-kv does guarantee (per-key total order + per-client
monotonicity) makes the CAS loop lose-and-retry rather than fork."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

node = Node()
KEY = "counter"


def read_total() -> int:
    try:
        return node.sync_rpc("seq-kv", {"type": "read", "key": KEY})["value"]
    except RPCError as e:
        if e.code != 20:
            raise
        return 0


@node.on("add")
def add(msg):
    delta = msg["body"]["delta"]
    if delta != 0:
        while True:
            cur = read_total()
            try:
                node.sync_rpc("seq-kv", {
                    "type": "cas", "key": KEY, "from": cur,
                    "to": cur + delta, "create_if_not_exists": True})
                break
            except RPCError as e:
                if e.code in (20, 22):
                    continue       # raced another add; retry on fresher state
                raise
    node.reply(msg, {"type": "add_ok"})


@node.on("read")
def read(msg):
    node.reply(msg, {"type": "read_ok", "value": read_total()})


if __name__ == "__main__":
    node.run()
