#!/usr/bin/env python3
"""Grow-only set demo node: periodic full-state gossip CRDT
(counterpart of demo/ruby/g_set.rb)."""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node

node = Node()
lock = threading.Lock()
elements = set()


@node.on("add")
def add(msg):
    with lock:
        elements.add(msg["body"]["element"])
    node.reply(msg, {"type": "add_ok"})


@node.on("read")
def read(msg):
    with lock:
        vals = sorted(elements)
    node.reply(msg, {"type": "read_ok", "value": vals})


@node.on("replicate")
def replicate(msg):
    with lock:
        elements.update(msg["body"]["value"])


@node.every(0.7)
def gossip():
    with lock:
        vals = sorted(elements)
    for other in node.node_ids:
        if other != node.node_id:
            node.send_msg(other, {"type": "replicate", "value": vals})


if __name__ == "__main__":
    node.run()
