#!/usr/bin/env python3
"""Echo server that reflects the entire request body back
(counterpart of demo/ruby/echo_full.rb)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node

node = Node()


@node.on("echo")
def echo(msg):
    node.reply(msg, dict(msg["body"], type="echo_ok"))


if __name__ == "__main__":
    node.run()
