#!/usr/bin/env python3
"""A generic state-based CRDT server (counterpart of demo/ruby/crdt.rb).

Wraps a Node around any CRDT value exposing:

  from_json(j)  inflate a value from a JSON structure
  to_json()     JSON structure for serialization
  merge(other)  a *new* value, this merged with other
  read()        the effective (client-visible) state

and serves:

  {type: "read"}               -> {type: "read_ok", value: <read()>}
  {type: "merge", value: <j>}  -> merges into local state; acked with
                                  {type: "merge_ok"} only when the request
                                  carries a msg_id (gossip replication is
                                  fire-and-forget and gets no reply)

replicating the full state to every other node every `interval_s` seconds.
Ships three value types: GSet, GCounter, PNCounter.
"""

from __future__ import annotations

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node


class CRDTServer:
    def __init__(self, node: Node, value, interval_s: float = 5.0):
        self.node = node
        self.value = value
        self.lock = threading.Lock()

        @node.on("read")
        def read(msg):
            with self.lock:
                v = self.value.read()
            node.reply(msg, {"type": "read_ok", "value": v})

        @node.on("merge")
        def merge(msg):
            with self.lock:
                other = self.value.from_json(msg["body"]["value"])
                self.value = self.value.merge(other)
                node.log(f"value now {self.value.to_json()}")
            # gossip merges are fire-and-forget (no msg_id); only ack
            # RPC-style merges
            if msg["body"].get("msg_id") is not None:
                node.reply(msg, {"type": "merge_ok"})

        @node.every(interval_s)
        def replicate():
            with self.lock:
                j = self.value.to_json()
            for other in node.node_ids:
                if other != node.node_id:
                    node.send_msg(other, {"type": "merge", "value": j})


class GSet:
    """Grow-only set."""

    def __init__(self, elements=()):
        self.elements = frozenset(elements)

    def from_json(self, j):
        return GSet(j)

    def to_json(self):
        return sorted(self.elements)

    def merge(self, other):
        return GSet(self.elements | other.elements)

    def read(self):
        return sorted(self.elements)

    def add(self, element):
        return GSet(self.elements | {element})


class GCounter:
    """Grow-only counter: one non-negative slot per node, merged by max."""

    def __init__(self, counts=None):
        self.counts = dict(counts or {})

    def from_json(self, j):
        return GCounter(j)

    def to_json(self):
        return dict(self.counts)

    def merge(self, other):
        merged = dict(self.counts)
        for k, v in other.counts.items():
            merged[k] = max(merged.get(k, 0), v)
        return GCounter(merged)

    def read(self):
        return sum(self.counts.values())

    def add(self, node_id, delta):
        assert delta >= 0
        c = dict(self.counts)
        c[node_id] = c.get(node_id, 0) + delta
        return GCounter(c)


class PNCounter:
    """Increment/decrement counter: a pair of GCounters."""

    def __init__(self, inc=None, dec=None):
        self.inc = inc or GCounter()
        self.dec = dec or GCounter()

    def from_json(self, j):
        return PNCounter(GCounter(j["inc"]), GCounter(j["dec"]))

    def to_json(self):
        return {"inc": self.inc.to_json(), "dec": self.dec.to_json()}

    def merge(self, other):
        return PNCounter(self.inc.merge(other.inc), self.dec.merge(other.dec))

    def read(self):
        return self.inc.read() - self.dec.read()

    def add(self, node_id, delta):
        if delta >= 0:
            return PNCounter(self.inc.add(node_id, delta), self.dec)
        return PNCounter(self.inc, self.dec.add(node_id, -delta))
