#!/usr/bin/env python3
"""Unique-id node (doc/tutorial/09-workloads.md): ids are
"<node_id>-<counter>" — node ids are unique by construction and the
counter is node-local, so no coordination (and no network traffic at
all) is needed for global uniqueness. Total availability under any
fault the nemesis can throw."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()
counter = 0


@node.on("generate")
def generate(msg):
    global counter
    counter += 1
    node.reply(msg, {"type": "generate_ok",
                     "id": f"{node.node_id}-{counter}"})


if __name__ == "__main__":
    node.run()
