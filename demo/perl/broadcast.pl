#!/usr/bin/env perl
# Broadcast demo node in Perl: neighbor gossip with retry until
# acknowledged, so values survive partitions (counterpart of
# demo/ruby/broadcast.rb and demo/python/broadcast.py).
use strict;
use warnings;
use FindBin;
use lib $FindBin::Bin;
use MaelstromNode;

my $node = MaelstromNode->new;
my %messages;           # value -> 1
my @neighbors;
my %unacked;            # neighbor -> { value -> 1 }

$node->on(topology => sub {
    my ($n, $msg) = @_;
    @neighbors = @{ $msg->{body}{topology}{ $n->{node_id} } // [] };
    $unacked{$_} //= {} for @neighbors;
    $n->log("My neighbors are @neighbors");
    $n->reply($msg, { type => "topology_ok" });
});

sub accept_value {
    my ($value, $sender) = @_;
    return if exists $messages{$value};
    $messages{$value} = 1;
    for my $nb (@neighbors) {
        $unacked{$nb}{$value} = 1
            unless defined $sender && $nb eq $sender;
    }
}

$node->on(broadcast => sub {
    my ($n, $msg) = @_;
    accept_value($msg->{body}{message}, $msg->{src});
    $n->reply($msg, { type => "broadcast_ok" })
        if defined $msg->{body}{msg_id};
});

$node->on(read => sub {
    my ($n, $msg) = @_;
    my @vals = sort { $a <=> $b } keys %messages;
    # numeric values round-trip as numbers
    $n->reply($msg, { type => "read_ok", messages => [map { $_ + 0 } @vals] });
});

# re-send unacknowledged values until the neighbor acks
$node->every(0.5 => sub {
    my ($n) = @_;
    for my $nb (keys %unacked) {
        for my $v (keys %{ $unacked{$nb} }) {
            $n->rpc($nb, { type => "broadcast", message => $v + 0 }, sub {
                delete $unacked{$nb}{$v};
            });
        }
    }
});

$node->run;
