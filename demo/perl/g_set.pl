#!/usr/bin/env perl
# Grow-only-set CRDT demo node in Perl: periodic full-state gossip to
# every peer, merge by union (counterpart of the reference's generic
# CRDT server, demo/ruby/crdt.rb, serving workload/g_set.clj).
use strict;
use warnings;
use FindBin;
use lib $FindBin::Bin;
use MaelstromNode;

my $node = MaelstromNode->new;
my %elements;

$node->on(add => sub {
    my ($n, $msg) = @_;
    $elements{ $msg->{body}{element} } = 1;
    $n->reply($msg, { type => "add_ok" });
});

$node->on(read => sub {
    my ($n, $msg) = @_;
    my @vals = sort { $a <=> $b } keys %elements;
    $n->reply($msg, { type => "read_ok", value => [map { $_ + 0 } @vals] });
});

$node->on(replicate => sub {
    my ($n, $msg) = @_;
    $elements{$_} = 1 for @{ $msg->{body}{value} };
});

$node->every(2.0 => sub {
    my ($n) = @_;
    my @vals = map { $_ + 0 } sort { $a <=> $b } keys %elements;
    for my $peer (@{ $n->{node_ids} }) {
        next if $peer eq $n->{node_id};
        $n->send_msg($peer, { type => "replicate", value => \@vals });
    }
});

$node->run;
