#!/usr/bin/env perl
# Echo server demo node in Perl (counterpart of demo/ruby/echo.rb and
# demo/python/echo.py).
use strict;
use warnings;
use FindBin;
use lib $FindBin::Bin;
use MaelstromNode;

my $node = MaelstromNode->new;

$node->on(echo => sub {
    my ($n, $msg) = @_;
    $n->reply($msg, { type => "echo_ok", echo => $msg->{body}{echo} });
});

$node->run;
