package MaelstromNode;

# A tiny node library for writing Maelstrom-protocol nodes in Perl —
# the third userland language next to demo/python/node.py and
# demo/c/maelstrom_node.h (the reference ships Ruby/Python/Clojure
# libraries; demo/ruby/node.rb:1-186 is the capability anchor).
#
# Newline-delimited JSON on stdin/stdout, logs on stderr
# (doc/protocol.md). Single-threaded: one select() loop dispatches
# incoming messages and fires periodic tasks between lines, so no
# locking is needed in handlers (the same run-to-completion model the
# C library uses).
#
# Surface:
#   my $node = MaelstromNode->new;
#   $node->on(echo => sub { my ($node, $msg) = @_; ... });
#   $node->every(0.5 => sub { ... });        # after init
#   $node->reply($msg, { type => "echo_ok" });
#   $node->rpc($dest, { type => ... }, sub { my ($node, $reply) = @_ });
#   $node->run;

use strict;
use warnings;
use JSON::PP;
use IO::Select;
use Time::HiRes qw(time);

my $json = JSON::PP->new->utf8->canonical;

sub new {
    my ($class) = @_;
    my $self = bless {
        node_id     => undef,
        node_ids    => [],
        next_msg_id => 0,
        handlers    => {},
        callbacks   => {},
        periodic    => [],    # [interval_s, next_due, fn]
        initialized => 0,
    }, $class;
    $self->on(init => sub {
        my ($node, $msg) = @_;
        $node->{node_id}  = $msg->{body}{node_id};
        $node->{node_ids} = $msg->{body}{node_ids};
        $node->{initialized} = 1;
        my $now = time;
        $_->[1] = $now + $_->[0] for @{ $node->{periodic} };
        $node->log("Node $node->{node_id} initialized");
        $node->reply($msg, { type => "init_ok" });
    });
    return $self;
}

sub on {
    my ($self, $type, $fn) = @_;
    die "already a handler for $type" if $self->{handlers}{$type};
    $self->{handlers}{$type} = $fn;
    return $self;
}

sub every {
    my ($self, $interval_s, $fn) = @_;
    push @{ $self->{periodic} }, [$interval_s, time + $interval_s, $fn];
    return $self;
}

sub log {
    my ($self, $text) = @_;
    print STDERR "$text\n";
    STDERR->flush;
}

sub send_msg {
    my ($self, $dest, $body) = @_;
    my $line = $json->encode(
        { src => $self->{node_id}, dest => $dest, body => $body });
    print STDOUT "$line\n";
    STDOUT->flush;
}

sub reply {
    my ($self, $request, $body) = @_;
    $self->send_msg($request->{src},
                    { %$body, in_reply_to => $request->{body}{msg_id} });
}

sub rpc {
    my ($self, $dest, $body, $callback, $timeout_s) = @_;
    my $msg_id = ++$self->{next_msg_id};
    # callbacks are reaped after timeout_s (default 5 s): a reply eaten
    # by a partition must not leak its closure forever (the C library
    # reaps via mn_rpc timeouts the same way)
    $self->{callbacks}{$msg_id} =
        [$callback, time + ($timeout_s // 5)] if $callback;
    $self->send_msg($dest, { %$body, msg_id => $msg_id });
    return $msg_id;
}

sub _reap_callbacks {
    my ($self) = @_;
    my $now = time;
    delete @{ $self->{callbacks} }
        { grep { $self->{callbacks}{$_}[1] < $now }
          keys %{ $self->{callbacks} } };
}

sub _dispatch {
    my ($self, $msg) = @_;
    my $body = $msg->{body};
    if (defined $body->{in_reply_to}) {
        my $cb = delete $self->{callbacks}{ $body->{in_reply_to} };
        $cb->[0]->($self, $msg) if $cb;
        return;
    }
    my $h = $self->{handlers}{ $body->{type} };
    if (!$h) {
        $self->log("No handler for $body->{type}");
        $self->reply($msg, { type => "error", code => 10,
                             text => "unsupported: $body->{type}" })
            if defined $body->{msg_id};
        return;
    }
    $h->($self, $msg);
}

sub _fire_periodic {
    my ($self) = @_;
    return unless $self->{initialized};
    my $now = time;
    for my $task (@{ $self->{periodic} }) {
        if ($now >= $task->[1]) {
            $task->[1] = $now + $task->[0];
            eval { $task->[2]->($self); 1 }
                or $self->log("periodic task error: $@");
        }
    }
}

sub _next_deadline {
    my ($self) = @_;
    return 1.0 unless $self->{initialized} && @{ $self->{periodic} };
    my $now = time;
    my $min = 1.0;
    for my $task (@{ $self->{periodic} }) {
        my $dt = $task->[1] - $now;
        $min = $dt if $dt < $min;
    }
    return $min > 0.01 ? $min : 0.01;
}

sub run {
    my ($self) = @_;
    my $sel = IO::Select->new(\*STDIN);
    my $buf = "";
    while (1) {
        $self->_fire_periodic;
        $self->_reap_callbacks;
        my @ready = $sel->can_read($self->_next_deadline);
        next unless @ready;
        my $n = sysread(STDIN, my $chunk, 65536);
        last unless $n;               # EOF: maelstrom is done with us
        $buf .= $chunk;
        while ($buf =~ s/^(.*?)\n//) {
            my $line = $1;
            next unless length $line;
            my $msg = eval { $json->decode($line) };
            if (!$msg) { $self->log("bad JSON: $@"); next; }
            $self->_dispatch($msg);
        }
    }
}

1;
