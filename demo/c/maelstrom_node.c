/* maelstrom_node.c — implementation of the reusable C node library.
 * See maelstrom_node.h for the API story. Single-threaded: one poll(2)
 * loop interleaves stdin lines with timer firings, so handlers and
 * periodic tasks never race (the same sequential-node model as the
 * reference's demo libraries). */

#define _POSIX_C_SOURCE 200809L   /* clock_gettime under -std=c99 */

#include "maelstrom_node.h"

#include <poll.h>
#include <stdarg.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

/* --- JSON scanning (string-aware, allocation-free) --- */

static size_t skip_string(const char *s, size_t i) {
    i++;
    while (s[i]) {
        if (s[i] == '\\' && s[i + 1]) i += 2;
        else if (s[i] == '"') return i + 1;
        else i++;
    }
    return i;
}

const char *mn_find(const char *s, const char *key) {
    size_t klen = strlen(key);
    size_t i = 0;
    while (s[i]) {
        if (s[i] == '"') {
            size_t start = i;
            i = skip_string(s, i);
            if (i - start - 2 == klen &&
                strncmp(s + start + 1, key, klen) == 0) {
                while (s[i] == ' ' || s[i] == '\t') i++;
                if (s[i] == ':') {
                    i++;
                    while (s[i] == ' ' || s[i] == '\t') i++;
                    return s + i;
                }
            }
        } else {
            i++;
        }
    }
    return NULL;
}

size_t mn_value_len(const char *v) {
    if (v[0] == '"') return skip_string(v, 0);
    if (v[0] == '{' || v[0] == '[') {
        char open = v[0], close = (open == '{') ? '}' : ']';
        int depth = 0;
        size_t i = 0;
        while (v[i]) {
            if (v[i] == '"') { i = skip_string(v, i); continue; }
            if (v[i] == open) depth++;
            else if (v[i] == close && --depth == 0) return i + 1;
            i++;
        }
        return i;
    }
    size_t i = 0;
    while (v[i] && !strchr(",}] \t\n", v[i])) i++;
    return i;
}

void mn_copy_str(const char *v, char *out, size_t cap) {
    out[0] = '\0';
    if (v && v[0] == '"') {
        size_t n = mn_value_len(v);
        if (n >= 2 && n - 2 < cap) {
            memcpy(out, v + 1, n - 2);
            out[n - 2] = '\0';
        }
    }
}

/* --- identity --- */

static char g_node_id[MN_ID_LEN] = "";
static char g_nodes[MN_MAX_NODES][MN_ID_LEN];
static int g_n_nodes = 0;
static void (*g_init_hook)(void) = NULL;

const char *mn_node_id(void) { return g_node_id; }
int mn_n_nodes(void) { return g_n_nodes; }
const char *mn_node_name(int i) { return g_nodes[i]; }
void mn_on_init(void (*fn)(void)) { g_init_hook = fn; }

/* --- handler registry --- */

#define MN_MAX_HANDLERS 32
static struct { char type[48]; void (*fn)(const mn_msg *); }
    g_handlers[MN_MAX_HANDLERS];
static int g_n_handlers = 0;

void mn_handle(const char *type, void (*h)(const mn_msg *m)) {
    if (g_n_handlers >= MN_MAX_HANDLERS) {
        fprintf(stderr, "mn: handler table full\n");
        exit(1);
    }
    snprintf(g_handlers[g_n_handlers].type,
             sizeof g_handlers[g_n_handlers].type, "%s", type);
    g_handlers[g_n_handlers].fn = h;
    g_n_handlers++;
}

/* --- sending --- */

static long g_next_id = 0;

static long send_body(const char *dest, long in_reply_to,
                      const char *fmt, va_list ap) {
    /* static: bodies can be large (a g-set snapshot is ~0.5 MB) and
     * the node is single-threaded, so one buffer serves every send */
    static char body[1 << 20];
    int w = vsnprintf(body, sizeof body, fmt, ap);
    if (w < 0 || (size_t)w >= sizeof body) {
        fprintf(stderr, "mn: body exceeds %zu bytes, dropped\n",
                sizeof body);
        return -1;
    }
    size_t blen = strlen(body);
    if (blen < 2 || body[0] != '{' || body[blen - 1] != '}') {
        fprintf(stderr, "mn: body must be a JSON object: %s\n", body);
        exit(1);
    }
    long mid = ++g_next_id;
    body[blen - 1] = '\0';            /* strip '}' to splice ids */
    printf("{\"src\": \"%s\", \"dest\": \"%s\", \"body\": %s%s"
           "\"msg_id\": %ld",
           g_node_id, dest, body, blen > 2 ? ", " : "", mid);
    if (in_reply_to >= 0) printf(", \"in_reply_to\": %ld", in_reply_to);
    printf("}}\n");
    fflush(stdout);
    return mid;
}

long mn_send(const char *dest, const char *fmt, ...) {
    va_list ap;
    va_start(ap, fmt);
    long mid = send_body(dest, -1, fmt, ap);
    va_end(ap);
    return mid;
}

long mn_reply(const mn_msg *m, const char *fmt, ...) {
    va_list ap;
    va_start(ap, fmt);
    long mid = send_body(m->src, m->msg_id, fmt, ap);
    va_end(ap);
    return mid;
}

/* --- RPC table --- */

#define MN_MAX_RPC 4096
static struct {
    long mid;                  /* full id; 0 = free slot */
    long deadline_ms;          /* monotonic ms, or 0 = no timeout */
    void (*cb)(const mn_msg *, void *);
    void *ctx;
} g_rpc[MN_MAX_RPC];

static long now_ms(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000L + ts.tv_nsec / 1000000L;
}

long mn_rpc(const char *dest, void (*cb)(const mn_msg *reply, void *ctx),
            void *ctx, long timeout_ms, const char *fmt, ...) {
    va_list ap;
    va_start(ap, fmt);
    long mid = send_body(dest, -1, fmt, ap);
    va_end(ap);
    if (mid < 0) {            /* body too large: fail like a timeout */
        if (cb) cb(NULL, ctx);
        return -1;
    }
    int slot = (int)(mid % MN_MAX_RPC);
    if (g_rpc[slot].mid != 0) {
        /* recycled before completion: fire its timeout now so no
         * callback is ever silently dropped */
        void (*old)(const mn_msg *, void *) = g_rpc[slot].cb;
        void *octx = g_rpc[slot].ctx;
        g_rpc[slot].mid = 0;
        if (old) old(NULL, octx);
    }
    g_rpc[slot].mid = mid;
    g_rpc[slot].deadline_ms = timeout_ms > 0 ? now_ms() + timeout_ms : 0;
    g_rpc[slot].cb = cb;
    g_rpc[slot].ctx = ctx;
    return mid;
}

static void rpc_tick(long t) {
    for (int i = 0; i < MN_MAX_RPC; i++) {
        if (g_rpc[i].mid != 0 && g_rpc[i].deadline_ms != 0 &&
            t >= g_rpc[i].deadline_ms) {
            void (*cb)(const mn_msg *, void *) = g_rpc[i].cb;
            void *ctx = g_rpc[i].ctx;
            g_rpc[i].mid = 0;
            if (cb) cb(NULL, ctx);
        }
    }
}

/* --- timers --- */

#define MN_MAX_TIMERS 16
static struct { long interval_ms; long due_ms; void (*fn)(void); }
    g_timers[MN_MAX_TIMERS];
static int g_n_timers = 0;

void mn_every(long interval_ms, void (*fn)(void)) {
    if (g_n_timers >= MN_MAX_TIMERS) {
        fprintf(stderr, "mn: timer table full\n");
        exit(1);
    }
    g_timers[g_n_timers].interval_ms = interval_ms;
    g_timers[g_n_timers].due_ms = now_ms() + interval_ms;
    g_timers[g_n_timers].fn = fn;
    g_n_timers++;
}

/* --- dispatch --- */

static void handle_init(const mn_msg *m) {
    mn_copy_str(mn_find(m->line, "node_id"), g_node_id,
                sizeof g_node_id);
    const char *ids = mn_find(m->line, "node_ids");
    g_n_nodes = 0;
    if (ids && ids[0] == '[') {
        size_t i = 1;
        while (ids[i] && ids[i] != ']') {
            if (ids[i] == '"') {
                size_t n = mn_value_len(ids + i);
                if (g_n_nodes < MN_MAX_NODES)
                    mn_copy_str(ids + i, g_nodes[g_n_nodes++],
                                MN_ID_LEN);
                i += n;
            } else {
                i++;
            }
        }
    }
    mn_reply(m, "{\"type\": \"init_ok\"}");
    if (g_init_hook) g_init_hook();
}

static void dispatch(const char *line) {
    mn_msg m;
    m.line = line;
    m.body = mn_find(line, "body");
    if (!m.body) return;
    mn_copy_str(mn_find(line, "src"), m.src, sizeof m.src);
    const char *t = mn_find(m.body, "type");
    mn_copy_str(t, m.type, sizeof m.type);
    const char *mid_v = mn_find(m.body, "msg_id");
    const char *irt_v = mn_find(m.body, "in_reply_to");
    m.msg_id = mid_v ? strtol(mid_v, NULL, 10) : -1;
    m.in_reply_to = irt_v ? strtol(irt_v, NULL, 10) : -1;

    if (m.in_reply_to >= 0) {
        int slot = (int)(m.in_reply_to % MN_MAX_RPC);
        if (g_rpc[slot].mid == m.in_reply_to) {   /* full-id check */
            void (*cb)(const mn_msg *, void *) = g_rpc[slot].cb;
            void *ctx = g_rpc[slot].ctx;
            g_rpc[slot].mid = 0;
            if (cb) cb(&m, ctx);
        }
        return;                                   /* late reply: drop */
    }
    if (strcmp(m.type, "init") == 0) {
        handle_init(&m);
        return;
    }
    for (int i = 0; i < g_n_handlers; i++) {
        if (strcmp(g_handlers[i].type, m.type) == 0) {
            g_handlers[i].fn(&m);
            return;
        }
    }
    mn_reply(&m, "{\"type\": \"error\", \"code\": 10, "
                 "\"text\": \"unsupported: %s\"}", m.type);
}

/* --- event loop --- */

int mn_run(void) {
    static char buf[1 << 20];
    size_t len = 0;
    struct pollfd pfd = { .fd = STDIN_FILENO, .events = POLLIN };
    for (;;) {
        long t = now_ms();
        rpc_tick(t);
        long wait = 1000;
        for (int i = 0; i < g_n_timers; i++) {
            if (g_timers[i].due_ms <= t) {
                g_timers[i].due_ms = t + g_timers[i].interval_ms;
                g_timers[i].fn();
            }
            long d = g_timers[i].due_ms - t;
            if (d < wait) wait = d;
        }
        for (int i = 0; i < MN_MAX_RPC; i++) {
            if (g_rpc[i].mid != 0 && g_rpc[i].deadline_ms != 0) {
                long d = g_rpc[i].deadline_ms - t;
                if (d < wait) wait = d;
            }
        }
        if (wait < 0) wait = 0;
        int r = poll(&pfd, 1, (int)wait);
        if (r <= 0) continue;
        if (pfd.revents & (POLLHUP | POLLERR) && !(pfd.revents & POLLIN))
            return 0;
        if (len >= sizeof buf - 1) {
            fprintf(stderr, "mn: input line exceeds %zu bytes\n",
                    sizeof buf);
            return 1;
        }
        ssize_t n = read(STDIN_FILENO, buf + len, sizeof buf - len - 1);
        if (n <= 0) return 0;                     /* EOF: clean exit */
        len += (size_t)n;
        buf[len] = '\0';
        char *start = buf;
        char *nl;
        while ((nl = strchr(start, '\n')) != NULL) {
            *nl = '\0';
            if (nl > start) dispatch(start);
            start = nl + 1;
        }
        len = (size_t)(buf + len - start);
        memmove(buf, start, len);
    }
}
