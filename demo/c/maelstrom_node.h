/* maelstrom_node.h — a reusable Maelstrom node library for C.
 *
 * The second *library* (not just node) language surface: the feature set
 * of the reference's demo/ruby/node.rb — a handler registry, periodic
 * tasks, and asynchronous RPC with per-request callbacks and timeouts —
 * rebuilt C-idiomatically on a poll(2) event loop, written against
 * doc/protocol.md alone. Demos link one .c file and register handlers:
 *
 *     #include "maelstrom_node.h"
 *     static void on_echo(const mn_msg *m) {
 *         const char *e = mn_find(m->body, "echo");
 *         mn_reply(m, "{\"type\": \"echo_ok\", \"echo\": %.*s}",
 *                  (int)mn_value_len(e), e);
 *     }
 *     int main(void) {
 *         mn_handle("echo", on_echo);
 *         return mn_run();
 *     }
 *
 * The library owns the stdio boundary: it parses each incoming line's
 * envelope (src, type, msg_id, in_reply_to), answers `init` itself
 * (recording node_id and the peer list), routes replies to their RPC
 * callbacks, stamps outgoing msg_ids, and drives `mn_every` timers from
 * the poll timeout. Handlers receive the raw line plus a pointer to the
 * body object and use mn_find/mn_value_len/mn_copy_str to pull fields —
 * values can be spliced verbatim into replies, so arbitrary scalar JSON
 * round-trips without a JSON library.
 */

#ifndef MAELSTROM_NODE_H
#define MAELSTROM_NODE_H

#include <stddef.h>

#define MN_ID_LEN 64
#define MN_MAX_NODES 128

typedef struct mn_msg {
    const char *line;    /* whole raw message line */
    const char *body;    /* pointer to the body object within line */
    char src[MN_ID_LEN];
    char type[48];
    long msg_id;         /* body msg_id, or -1 */
    long in_reply_to;    /* body in_reply_to, or -1 */
} mn_msg;

/* --- JSON field access (string-aware scanner, no allocation) --- */

/* Pointer to the value of `key` anywhere in `s`, or NULL. */
const char *mn_find(const char *s, const char *key);
/* Token length of the value at `v` (string/object/array/scalar). */
size_t mn_value_len(const char *v);
/* Copy a JSON string value (sans quotes) into out; "" when absent. */
void mn_copy_str(const char *v, char *out, size_t cap);

/* --- identity (valid after init; mn_run handles init itself) --- */

const char *mn_node_id(void);
int mn_n_nodes(void);
const char *mn_node_name(int i);          /* all nodes, including self */

/* Optional hook invoked once after init_ok is sent. */
void mn_on_init(void (*fn)(void));

/* --- handlers --- */

/* Register `h` for body type `type` (non-reply messages). */
void mn_handle(const char *type, void (*h)(const mn_msg *m));

/* --- sending --- */

/* Send a body (printf-style; the body must be a JSON object literal —
 * the library splices a fresh msg_id into it). Returns the msg_id. */
long mn_send(const char *dest, const char *fmt, ...);
/* Reply to `m`: splices msg_id AND in_reply_to. */
long mn_reply(const mn_msg *m, const char *fmt, ...);

/* Async RPC: send a body and register a callback for its reply. The
 * callback fires once — with the reply, or with reply == NULL when
 * timeout_ms elapses first (retry by issuing a fresh mn_rpc). A late
 * reply after the timeout is dropped (the slot remembers its full
 * msg_id, so a recycled slot can never mis-ack). */
long mn_rpc(const char *dest, void (*cb)(const mn_msg *reply, void *ctx),
            void *ctx, long timeout_ms, const char *fmt, ...);

/* --- periodic tasks --- */

/* Run `fn` every interval_ms (first firing after one interval). */
void mn_every(long interval_ms, void (*fn)(void));

/* --- event loop: poll stdin + timers; returns on EOF --- */

int mn_run(void);

#endif /* MAELSTROM_NODE_H */
