/* Echo on the C node library — the doc/tutorial "hello world" showing
 * how small a node gets once maelstrom_node.h owns the stdio boundary
 * (compare echo.c, which hand-rolls the same loop in ~150 lines).
 *
 * Build: make -C demo/c    Run: ... test -w echo --bin demo/c/echo_lib
 */

#include "maelstrom_node.h"

static void on_echo(const mn_msg *m) {
    const char *e = mn_find(m->body, "echo");
    mn_reply(m, "{\"type\": \"echo_ok\", \"echo\": %.*s}",
             e ? (int)mn_value_len(e) : 4, e ? e : "null");
}

int main(void) {
    mn_handle("echo", on_echo);
    return mn_run();
}
