/* A Maelstrom-protocol broadcast node in C: gossip with retry-until-ack,
 * written against doc/protocol.md + doc/workloads.md alone — the
 * second-language proof that the documented stdio boundary suffices for a
 * non-trivial, partition-tolerant node (the counterpart of the
 * reference's multi-language demo surface, demo/ruby/raft.rb etc).
 *
 * Protocol served (doc/workloads.md "broadcast"):
 *   topology  -> topology_ok  (records this node's neighbor list)
 *   broadcast -> broadcast_ok (new message: remember + gossip out)
 *   read      -> read_ok {"messages": [...]}
 * Inter-node:
 *   gossip {"message": v} -> gossip_ok (reply ack)
 *
 * Every seen value is gossiped to every neighbor until that neighbor
 * acks it; unacked values retransmit on a 250 ms tick, so partitions
 * and message loss only delay convergence. Values are stored as raw
 * JSON tokens and spliced verbatim into replies, so any scalar payload
 * round-trips exactly.
 *
 * No JSON library: the same string-aware scanner as echo.c. Input is
 * read with poll() + a hand-rolled line buffer (stdio's fgets would
 * block the retry tick).
 *
 * Build: make -C demo/c    Run: ... test -w broadcast --bin demo/c/broadcast
 */

#include <poll.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#define MAX_VALUES 8192
#define MAX_NBRS 32
#define VAL_LEN 64
#define ID_LEN 64
#define MAX_RPC (1 << 20)

static size_t skip_string(const char *s, size_t i) {
    i++;
    while (s[i]) {
        if (s[i] == '\\' && s[i + 1]) i += 2;
        else if (s[i] == '"') return i + 1;
        else i++;
    }
    return i;
}

static const char *find_value(const char *s, const char *key) {
    size_t klen = strlen(key);
    size_t i = 0;
    while (s[i]) {
        if (s[i] == '"') {
            size_t start = i;
            i = skip_string(s, i);
            if (i - start - 2 == klen &&
                strncmp(s + start + 1, key, klen) == 0) {
                while (s[i] == ' ' || s[i] == '\t') i++;
                if (s[i] == ':') {
                    i++;
                    while (s[i] == ' ' || s[i] == '\t') i++;
                    return s + i;
                }
            }
        } else {
            i++;
        }
    }
    return NULL;
}

static size_t value_len(const char *v) {
    if (v[0] == '"') return skip_string(v, 0);
    if (v[0] == '{' || v[0] == '[') {
        char open = v[0], close = (open == '{') ? '}' : ']';
        int depth = 0;
        size_t i = 0;
        while (v[i]) {
            if (v[i] == '"') { i = skip_string(v, i); continue; }
            if (v[i] == open) depth++;
            else if (v[i] == close && --depth == 0) return i + 1;
            i++;
        }
        return i;
    }
    size_t i = 0;
    while (v[i] && !strchr(",}] \t\n", v[i])) i++;
    return i;
}

/* Copies a JSON string value (sans quotes) into out. */
static void copy_str(const char *v, char *out, size_t cap) {
    out[0] = '\0';
    if (v && v[0] == '"') {
        size_t n = value_len(v);
        if (n >= 2 && n - 2 < cap) {
            memcpy(out, v + 1, n - 2);
            out[n - 2] = '\0';
        }
    }
}

/* --- node state --- */

static char node_id[ID_LEN] = "";
static long next_id = 0;

static char values[MAX_VALUES][VAL_LEN];   /* raw JSON tokens */
static int n_values = 0;

static char nbrs[MAX_NBRS][ID_LEN];
static int n_nbrs = 0;

/* acked[nb][val]: neighbor nb has acknowledged value val */
static unsigned char acked[MAX_NBRS][MAX_VALUES];

/* outstanding gossip RPCs: msg_id -> (nb, val), -1 = free. Slots are
 * indexed msg_id % MAX_RPC; rpc_mid holds the full id so a late ack for
 * a wrapped-around old id can't mark a reused slot's pair acked. */
static int rpc_nb[MAX_RPC];
static int rpc_val[MAX_RPC];
static long rpc_mid[MAX_RPC];

static int find_or_add_value(const char *tok, size_t n) {
    if (n >= VAL_LEN) n = VAL_LEN - 1;
    for (int i = 0; i < n_values; i++)
        if (strlen(values[i]) == n && strncmp(values[i], tok, n) == 0)
            return i;
    if (n_values >= MAX_VALUES) {
        fprintf(stderr, "value table full\n");
        return -1;
    }
    memcpy(values[n_values], tok, n);
    values[n_values][n] = '\0';
    return n_values++;
}

static int nbr_index(const char *id) {
    for (int i = 0; i < n_nbrs; i++)
        if (strcmp(nbrs[i], id) == 0) return i;
    return -1;
}

static void send_gossip(int nb, int val) {
    long mid = ++next_id;
    rpc_nb[mid % MAX_RPC] = nb;
    rpc_val[mid % MAX_RPC] = val;
    rpc_mid[mid % MAX_RPC] = mid;
    printf("{\"src\": \"%s\", \"dest\": \"%s\", \"body\": "
           "{\"type\": \"gossip\", \"msg_id\": %ld, \"message\": %s}}\n",
           node_id, nbrs[nb], mid, values[val]);
}

/* Retransmit every unacked (neighbor, value) pair. Gossip is
 * idempotent, so duplicates are harmless; acks stop the traffic. */
static void tick(void) {
    for (int nb = 0; nb < n_nbrs; nb++)
        for (int v = 0; v < n_values; v++)
            if (!acked[nb][v]) send_gossip(nb, v);
    fflush(stdout);
}

static void handle_line(const char *line) {
    const char *src_v = find_value(line, "src");
    const char *mid_v = find_value(line, "msg_id");
    const char *type_v = find_value(line, "type");
    const char *irt_v = find_value(line, "in_reply_to");
    char src[ID_LEN];
    copy_str(src_v, src, sizeof src);
    long in_reply_to = mid_v ? strtol(mid_v, NULL, 10) : -1;

    if (irt_v) {                       /* a reply: gossip_ok ack */
        long mid = strtol(irt_v, NULL, 10);
        int slot = (int)(mid % MAX_RPC);
        if (rpc_nb[slot] >= 0 && rpc_mid[slot] == mid) {
            acked[rpc_nb[slot]][rpc_val[slot]] = 1;
            rpc_nb[slot] = -1;
        }
        return;
    }
    if (!type_v) return;

    if (strncmp(type_v, "\"init\"", 6) == 0) {
        copy_str(find_value(line, "node_id"), node_id, sizeof node_id);
        fprintf(stderr, "node %s initialized\n", node_id);
        printf("{\"src\": \"%s\", \"dest\": \"%s\", \"body\": "
               "{\"type\": \"init_ok\", \"msg_id\": %ld, "
               "\"in_reply_to\": %ld}}\n",
               node_id, src, ++next_id, in_reply_to);
    } else if (strncmp(type_v, "\"topology\"", 10) == 0) {
        /* our row: "<node_id>": [ "n1", "n2", ... ] */
        const char *topo = find_value(line, "topology");
        const char *row = topo ? find_value(topo, node_id) : NULL;
        n_nbrs = 0;
        if (row && row[0] == '[') {
            size_t i = 1;
            while (row[i] && row[i] != ']' && n_nbrs < MAX_NBRS) {
                if (row[i] == '"') {
                    size_t end = skip_string(row, i);
                    size_t n = end - i - 2;
                    if (n < ID_LEN) {
                        memcpy(nbrs[n_nbrs], row + i + 1, n);
                        nbrs[n_nbrs][n] = '\0';
                        n_nbrs++;
                    }
                    i = end;
                } else {
                    i++;
                }
            }
        }
        fprintf(stderr, "topology: %d neighbors\n", n_nbrs);
        printf("{\"src\": \"%s\", \"dest\": \"%s\", \"body\": "
               "{\"type\": \"topology_ok\", \"msg_id\": %ld, "
               "\"in_reply_to\": %ld}}\n",
               node_id, src, ++next_id, in_reply_to);
    } else if (strncmp(type_v, "\"broadcast\"", 11) == 0 ||
               strncmp(type_v, "\"gossip\"", 8) == 0) {
        int is_gossip = type_v[1] == 'g';
        const char *msg = find_value(line, "message");
        int before = n_values;
        int val = msg ? find_or_add_value(msg, value_len(msg)) : -1;
        if (val >= 0 && val == before) {       /* genuinely new */
            int from = is_gossip ? nbr_index(src) : -1;
            for (int nb = 0; nb < n_nbrs; nb++) {
                /* the gossiping sender has it by definition */
                if (nb == from) acked[nb][val] = 1;
                else send_gossip(nb, val);
            }
        } else if (val >= 0 && is_gossip) {
            int from = nbr_index(src);
            if (from >= 0) acked[from][val] = 1;  /* they have it too */
        }
        printf("{\"src\": \"%s\", \"dest\": \"%s\", \"body\": "
               "{\"type\": \"%s\", \"msg_id\": %ld, "
               "\"in_reply_to\": %ld}}\n",
               node_id, src, is_gossip ? "gossip_ok" : "broadcast_ok",
               ++next_id, in_reply_to);
    } else if (strncmp(type_v, "\"read\"", 6) == 0) {
        printf("{\"src\": \"%s\", \"dest\": \"%s\", \"body\": "
               "{\"type\": \"read_ok\", \"msg_id\": %ld, "
               "\"in_reply_to\": %ld, \"messages\": [",
               node_id, src, ++next_id, in_reply_to);
        for (int i = 0; i < n_values; i++)
            printf("%s%s", i ? ", " : "", values[i]);
        printf("]}}\n");
    } else if (mid_v) {
        printf("{\"src\": \"%s\", \"dest\": \"%s\", \"body\": "
               "{\"type\": \"error\", \"code\": 10, \"msg_id\": %ld, "
               "\"in_reply_to\": %ld, "
               "\"text\": \"unsupported message type\"}}\n",
               node_id, src, ++next_id, in_reply_to);
    }
    fflush(stdout);
}

int main(void) {
    static char buf[1 << 20];
    size_t used = 0;
    memset(rpc_nb, -1, sizeof rpc_nb);

    for (;;) {
        struct pollfd pfd = {STDIN_FILENO, POLLIN, 0};
        int r = poll(&pfd, 1, 250);
        if (r < 0) break;
        if (r == 0) { tick(); continue; }
        if (pfd.revents & (POLLERR | POLLNVAL)) break;
        ssize_t n = read(STDIN_FILENO, buf + used, sizeof buf - used - 1);
        if (n <= 0) break;            /* EOF: harness teardown */
        used += (size_t)n;
        buf[used] = '\0';
        char *start = buf;
        char *nl;
        while ((nl = strchr(start, '\n'))) {
            *nl = '\0';
            if (*start) handle_line(start);
            start = nl + 1;
        }
        used = (size_t)(buf + used - start);
        memmove(buf, start, used);
    }
    return 0;
}
