/* A Maelstrom-protocol echo node in C — proof that nodes are ordinary
 * binaries in any language (doc/protocol.md; the counterpart of the
 * reference's multi-language demo surface, demo/ruby + demo/clojure).
 *
 * Reads newline-delimited JSON messages on stdin, answers `init` with
 * `init_ok` and `echo` with `echo_ok`, logs to stderr. No JSON library:
 * a small string-aware scanner extracts the fields this protocol needs
 * (msg_id, src, and the raw text of the "echo" value, spliced verbatim
 * into the reply so any JSON payload round-trips exactly).
 *
 * Build: make -C demo/c     Run: ./maelstrom test -w echo --bin demo/c/echo
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

/* Skips a JSON string starting at s (s[0] == '"'); returns the index
 * one past the closing quote, honoring backslash escapes. */
static size_t skip_string(const char *s, size_t i) {
    i++; /* opening quote */
    while (s[i]) {
        if (s[i] == '\\' && s[i + 1]) i += 2;
        else if (s[i] == '"') return i + 1;
        else i++;
    }
    return i;
}

/* Finds the start of the value for top-level-ish key `key` ("\"key\"")
 * anywhere in the object text, skipping matches inside strings. Returns
 * NULL if absent. Good enough for this protocol: the harness never
 * nests an "echo"/"msg_id"/"src" key inside another object before the
 * real one. */
static const char *find_value(const char *s, const char *key) {
    size_t klen = strlen(key);
    size_t i = 0;
    while (s[i]) {
        if (s[i] == '"') {
            size_t start = i;
            i = skip_string(s, i);
            if (i - start - 2 == klen && strncmp(s + start + 1, key, klen) == 0) {
                while (s[i] == ' ' || s[i] == '\t') i++;
                if (s[i] == ':') {
                    i++;
                    while (s[i] == ' ' || s[i] == '\t') i++;
                    return s + i;
                }
            }
        } else {
            i++;
        }
    }
    return NULL;
}

/* Length of the JSON value starting at v: a string, or a balanced
 * object/array, or a bare literal (number/true/false/null). */
static size_t value_len(const char *v) {
    if (v[0] == '"') return skip_string(v, 0);
    if (v[0] == '{' || v[0] == '[') {
        char open = v[0], close = (open == '{') ? '}' : ']';
        int depth = 0;
        size_t i = 0;
        while (v[i]) {
            if (v[i] == '"') { i = skip_string(v, i); continue; }
            if (v[i] == open) depth++;
            else if (v[i] == close && --depth == 0) return i + 1;
            i++;
        }
        return i;
    }
    size_t i = 0;
    while (v[i] && !strchr(",}] \t\n", v[i])) i++;
    return i;
}

int main(void) {
    static char line[1 << 20];
    char node_id[64] = "";
    long next_id = 0;

    while (fgets(line, sizeof line, stdin)) {
        const char *src_v = find_value(line, "src");
        const char *mid_v = find_value(line, "msg_id");
        const char *type_v = find_value(line, "type");
        if (!src_v || !type_v) continue;

        char src[64] = "";
        if (src_v[0] == '"') {
            size_t n = value_len(src_v);
            if (n >= 2 && n - 2 < sizeof src) {
                memcpy(src, src_v + 1, n - 2);
                src[n - 2] = '\0';
            }
        }
        long in_reply_to = mid_v ? strtol(mid_v, NULL, 10) : -1;

        if (strncmp(type_v, "\"init\"", 6) == 0) {
            const char *nid = find_value(line, "node_id");
            if (nid && nid[0] == '"') {
                size_t n = value_len(nid);
                if (n >= 2 && n - 2 < sizeof node_id) {
                    memcpy(node_id, nid + 1, n - 2);
                    node_id[n - 2] = '\0';
                }
            }
            fprintf(stderr, "node %s initialized\n", node_id);
            printf("{\"src\": \"%s\", \"dest\": \"%s\", \"body\": "
                   "{\"type\": \"init_ok\", \"msg_id\": %ld, "
                   "\"in_reply_to\": %ld}}\n",
                   node_id, src, ++next_id, in_reply_to);
            fflush(stdout);
        } else if (strncmp(type_v, "\"echo\"", 6) == 0) {
            const char *echo_v = find_value(line, "echo");
            size_t n = echo_v ? value_len(echo_v) : 4;
            printf("{\"src\": \"%s\", \"dest\": \"%s\", \"body\": "
                   "{\"type\": \"echo_ok\", \"msg_id\": %ld, "
                   "\"in_reply_to\": %ld, \"echo\": %.*s}}\n",
                   node_id, src, ++next_id, in_reply_to,
                   (int)n, echo_v ? echo_v : "null");
            fflush(stdout);
        } else if (mid_v) {
            printf("{\"src\": \"%s\", \"dest\": \"%s\", \"body\": "
                   "{\"type\": \"error\", \"code\": 10, \"msg_id\": %ld, "
                   "\"in_reply_to\": %ld, "
                   "\"text\": \"unsupported message type\"}}\n",
                   node_id, src, ++next_id, in_reply_to);
            fflush(stdout);
        }
    }
    return 0;
}
