/* A grow-only set (g-set workload) on the C node library — the port
 * that proves maelstrom_node.h's full surface: handler registry for the
 * client RPCs, an `mn_every` periodic task for anti-entropy, and
 * `mn_rpc` callbacks for acked replication with retry-on-timeout.
 *
 * Protocol served (doc/workloads.md "g-set"):
 *   add  {"element": e} -> add_ok
 *   read                -> read_ok {"value": [...]}
 * Inter-node:
 *   replicate {"value": [...]} -> replicate_ok
 *
 * Replication: every 200 ms each peer that has not acknowledged this
 * node's current set gets the full set over an RPC; the reply callback
 * records how much that peer has confirmed, a timeout simply leaves the
 * peer dirty for the next tick. Unions are idempotent, so loss,
 * duplication, and partitions only delay convergence — add availability
 * is total (every node accepts adds), exactly the CRDT story of the
 * reference's g-set demos.
 *
 * Build: make -C demo/c    Run: ... test -w g-set --bin demo/c/gset
 */

#include <stdio.h>
#include <string.h>

#include "maelstrom_node.h"

#define MAX_ELEMS 8192
#define ELEM_LEN 64

static char elems[MAX_ELEMS][ELEM_LEN];    /* raw JSON tokens */
static int n_elems = 0;

/* acked_upto[i]: how many of our elements peer i has confirmed (our
 * set only grows and replicate carries a full prefix-closed snapshot,
 * so a count is a complete acknowledgement state) */
static int acked_upto[MN_MAX_NODES];

static int find_or_add(const char *tok, size_t n) {
    if (n == 0 || n >= ELEM_LEN) return -1;
    for (int i = 0; i < n_elems; i++)
        if (strlen(elems[i]) == n && strncmp(elems[i], tok, n) == 0)
            return i;
    if (n_elems >= MAX_ELEMS) {
        fprintf(stderr, "gset: element table full\n");
        return -1;
    }
    memcpy(elems[n_elems], tok, n);
    elems[n_elems][n] = '\0';
    return n_elems++;
}

static size_t render_set(char *out, size_t cap, int upto) {
    size_t w = 0;
    out[w++] = '[';
    for (int i = 0; i < upto && w + ELEM_LEN + 4 < cap; i++) {
        if (i) out[w++] = ',';
        w += (size_t)snprintf(out + w, cap - w, "%s", elems[i]);
    }
    out[w++] = ']';
    out[w] = '\0';
    return w;
}

static void absorb_array(const char *arr) {
    if (!arr || arr[0] != '[') return;
    size_t i = 1;
    while (arr[i] && arr[i] != ']') {
        if (arr[i] == ' ' || arr[i] == ',' || arr[i] == '\t') {
            i++;
            continue;
        }
        size_t n = mn_value_len(arr + i);
        find_or_add(arr + i, n);
        i += n;
    }
}

static void on_add(const mn_msg *m) {
    const char *e = mn_find(m->body, "element");
    if (!e || find_or_add(e, mn_value_len(e)) < 0) {
        /* never ack a dropped element — an acked-then-missing element
         * is exactly what the set-full checker calls "lost". Code 11
         * is DEFINITE (temporarily-unavailable): the add certainly did
         * not happen, so the checker grades a clean fail, not an
         * indeterminate the set must carry forever. */
        mn_reply(m, "{\"type\": \"error\", \"code\": 11, "
                    "\"text\": \"element rejected (size or capacity)\"}");
        return;
    }
    mn_reply(m, "{\"type\": \"add_ok\"}");
}

static void on_read(const mn_msg *m) {
    static char set[MAX_ELEMS * (ELEM_LEN + 1) + 8];
    render_set(set, sizeof set, n_elems);
    mn_reply(m, "{\"type\": \"read_ok\", \"value\": %s}", set);
}

static void on_replicate(const mn_msg *m) {
    absorb_array(mn_find(m->body, "value"));
    mn_reply(m, "{\"type\": \"replicate_ok\"}");
}

/* reply callback: peer `ctx` confirmed the snapshot we sent it. One
 * RPC in flight per peer (inflight guard): a second overlapping
 * snapshot could otherwise get acked by the FIRST snapshot's reply,
 * over-acknowledging elements the peer may never have received. */
static long sent_upto[MN_MAX_NODES];
static int inflight[MN_MAX_NODES];

static void on_replicate_ack(const mn_msg *reply, void *ctx) {
    int peer = (int)(long)ctx;
    inflight[peer] = 0;
    if (reply != NULL && sent_upto[peer] > acked_upto[peer])
        acked_upto[peer] = (int)sent_upto[peer];
    /* timeout (reply == NULL): leave the peer dirty; the next tick
     * retransmits the then-current snapshot */
}

static void anti_entropy(void) {
    static char set[MAX_ELEMS * (ELEM_LEN + 1) + 8];
    for (int i = 0; i < mn_n_nodes(); i++) {
        const char *peer = mn_node_name(i);
        if (strcmp(peer, mn_node_id()) == 0) continue;
        if (inflight[i] || acked_upto[i] >= n_elems) continue;
        render_set(set, sizeof set, n_elems);
        sent_upto[i] = n_elems;
        inflight[i] = 1;
        mn_rpc(peer, on_replicate_ack, (void *)(long)i, 1000,
               "{\"type\": \"replicate\", \"value\": %s}", set);
    }
}

int main(void) {
    mn_handle("add", on_add);
    mn_handle("read", on_read);
    mn_handle("replicate", on_replicate);
    mn_every(200, anti_entropy);
    return mn_run();
}
