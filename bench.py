#!/usr/bin/env python
"""Headline benchmark: simulated message throughput for the broadcast
workload at 100k nodes on one chip (BASELINE.json north star: >= 1M
simulated msgs/sec, converged under the broadcast semantics).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "msgs/sec", "vs_baseline": N/1e6, ...}

Config via env: BENCH_NODES, BENCH_VALUES, BENCH_ROUNDS, BENCH_POOL.
Runs on whatever JAX's default backend is (the real TPU under the driver);
the whole R-round simulation executes as one lax.scan dispatch, so host
latency does not pollute the measurement. The first call compiles (excluded
from timing); the timed call reuses the cached executable on fresh state.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# persist compiled executables across bench invocations (the box — and
# this directory — survives between rounds, though the cache blobs stay
# uncommitted): a recapture after a tunnel outage then costs seconds of
# compile, not ~70 s per attempt inside a flaky window
_CACHE_DEFAULT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "artifacts", "xla-cache")
if os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                         _CACHE_DEFAULT) == _CACHE_DEFAULT:
    # only materialize OUR default — an operator override (possibly a
    # gs:// remote cache) passes through untouched
    os.makedirs(_CACHE_DEFAULT, exist_ok=True)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")


_ENV_ERROR_MARKS = (
    "Unable to initialize backend", "UNAVAILABLE", "DEADLINE_EXCEEDED",
    "Socket closed", "failed to connect", "Connection reset",
)


def _is_env_error(exc: BaseException) -> bool:
    """True when the failure is the tunneled TPU backend being down, not
    a bug in the benchmark (r3 lesson: one transient backend-init failure
    lost the whole round's artifact)."""
    text = f"{type(exc).__name__}: {exc}"
    return any(m in text for m in _ENV_ERROR_MARKS)


def run_with_env_retry(fn, attempts=3, backoff_s=60,
                       metric="broadcast_sim_msgs_per_sec_100k_nodes",
                       unit="msgs/sec"):
    """Run `fn`; on an environmental (backend-unavailable) failure, clear
    the half-initialized backend and retry up to `attempts` times with
    `backoff_s` sleeps. On final environmental failure emit a JSON record
    with "env_unavailable": true — machine-distinguishable from a
    regression — and exit 3. Non-environmental errors propagate."""
    last = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - filtered by _is_env_error
            if not _is_env_error(e):
                raise
            last = e
            print(f"bench: backend unavailable (attempt {i + 1}/"
                  f"{attempts}): {e}", file=sys.stderr)
            try:
                import jax._src.xla_bridge as xb
                xb._clear_backends()
            except Exception as ce:  # private API — may vanish in a
                #                      jax upgrade; make that visible
                print(f"bench: backend reset unavailable "
                      f"({type(ce).__name__}: {ce}) — retrying against "
                      f"the existing backend state", file=sys.stderr)
            if i < attempts - 1:
                time.sleep(backoff_s)
    print(json.dumps({
        "metric": metric,
        "value": None, "unit": unit, "vs_baseline": None,
        "env_unavailable": True,
        "error": f"{type(last).__name__}: {last}",
        "attempts": attempts,
    }))
    sys.exit(3)


def bench_raft_clusters():
    """Secondary benchmark: 10k independent 5-node raft clusters advance
    under one vmap (BASELINE config 4). Metric: cluster-rounds/sec —
    simulated raft rounds x clusters per wall second — plus a leader-
    election sanity check."""
    import jax

    from maelstrom_tpu.net import tpu as T
    from maelstrom_tpu.nodes import get_program
    from maelstrom_tpu.parallel import make_cluster_round_fn, \
        make_cluster_sims

    n = int(os.environ.get("BENCH_RAFT_NODES", 5))
    clusters = int(os.environ.get("BENCH_RAFT_CLUSTERS", 10_000))
    R = int(os.environ.get("BENCH_ROUNDS", 300))
    chunk = min(int(os.environ.get("BENCH_CHUNK", 100)), R)

    nodes = [f"n{i}" for i in range(n)]
    program = get_program("lin-kv", {"latency": {"mean": 0}}, nodes)
    cfg = T.NetConfig(n_nodes=n, n_clients=1, pool_cap=64,
                      inbox_cap=program.inbox_cap, client_cap=4)
    round_fn = make_cluster_round_fn(program, cfg)
    scan = jax.jit(lambda sims, _: jax.lax.scan(
        lambda s, x: (round_fn(s, T.Msgs.empty((clusters, 1)))[0], None),
        sims, None, length=chunk)[0])

    def run(sims):
        for _ in range(R // chunk):
            sims = scan(sims, None)
        assert int(jax.device_get(sims.net.round[0])) == \
            (R // chunk) * chunk
        return sims

    print(f"bench[raft]: {clusters} clusters x {n} nodes, {R} rounds",
          file=sys.stderr)
    sims0 = make_cluster_sims(program, cfg, clusters, seed=0)
    sims1 = make_cluster_sims(program, cfg, clusters, seed=1)
    t0 = time.perf_counter()
    run(sims0)
    print(f"bench[raft]: compile+first run {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)
    t0 = time.perf_counter()
    sims = run(sims1)              # sims built outside the timed window
    dt = time.perf_counter() - t0

    import numpy as np
    roles = np.asarray(jax.device_get(sims.nodes["role"]))
    one_leader = float(((roles == 2).sum(axis=1) == 1).mean())
    rounds_done = (R // chunk) * chunk
    rate = rounds_done * clusters / dt
    record = {
        "metric": "raft_cluster_rounds_per_sec_10k_clusters",
        "value": round(rate, 1), "unit": "cluster-rounds/sec",
        "vs_baseline": round(rate / 1e6, 4),
        "clusters": clusters, "nodes_per_cluster": n,
        "rounds": rounds_done, "wall_s": round(dt, 3),
        "clusters_with_one_leader": one_leader,
    }

    # grading half: real contending client traffic into a sampled subset
    # of the same-size vmapped fleet, every sampled history graded by
    # the stock WGL linearizability checker — with a partition nemesis
    # ACTIVE during the graded window (every cluster gets an independent
    # majority/minority split, healed before each worker's final read)
    if os.environ.get("BENCH_RAFT_GRADED", "1") == "1":
        from maelstrom_tpu.bench_raft_graded import run_raft_graded
        g = run_raft_graded(
            n_clusters=clusters, n=n,
            sample=int(os.environ.get("BENCH_RAFT_SAMPLE", 512)),
            ops_per_client=int(os.environ.get("BENCH_RAFT_OPS", 50)),
            partition_at=int(os.environ.get("BENCH_RAFT_PART_AT", 20)),
            partition_chunks=int(
                os.environ.get("BENCH_RAFT_PART_CHUNKS", 30)),
            max_chunks=800,
            seed=3)
        record["graded"] = g
        record["sampled_clusters"] = g["sampled_clusters"]
        record["all_linearizable"] = g["all_linearizable"]
    print(json.dumps(record))
    if record.get("all_linearizable") is False:
        sys.exit(1)
    if one_leader < 1.0:
        sys.exit(1)


def main():
    from maelstrom_tpu.util import honor_jax_platforms
    honor_jax_platforms()   # JAX_PLATFORMS=cpu smoke runs; no-op unset
    if os.environ.get("BENCH_MODE") == "raft":
        return run_with_env_retry(
            bench_raft_clusters,
            metric="raft_cluster_rounds_per_sec_10k_clusters",
            unit="cluster-rounds/sec")
    return run_with_env_retry(_main_broadcast)


def _main_broadcast():
    import jax
    import jax.numpy as jnp

    from maelstrom_tpu.net import tpu as T
    from maelstrom_tpu.nodes import get_program
    from maelstrom_tpu.nodes.broadcast import T_BCAST
    from maelstrom_tpu.sim import make_run_fn, make_sim

    N = int(os.environ.get("BENCH_NODES", 100_000))
    V = int(os.environ.get("BENCH_VALUES", 64))
    # 700 rounds: injections end at round 128 and the deterministic
    # zero-latency grid flood completes before 700 (the run exits nonzero
    # if convergence is ever lost); more rounds only add idle tail
    R = int(os.environ.get("BENCH_ROUNDS", 700))
    # rounds per scan dispatch: long single dispatches (>~60 s device time)
    # are killed by the remote-TPU tunnel, so the scan is chunked
    chunk = int(os.environ.get("BENCH_CHUNK", 100))
    pool_cap = int(os.environ.get("BENCH_POOL", 8192))
    R = max(chunk, (R // chunk) * chunk)   # at least one chunk

    # Eager-resend gossip maximizes per-round message load (pending values
    # retransmit until digest-acked); the efficient send-once protocol is
    # the interactive default. Both converge; this knob only changes how
    # much traffic the network is asked to simulate.
    eager = os.environ.get("BENCH_EAGER", "1") == "1"
    nodes = [f"n{i}" for i in range(N)]
    # one gossip lane per edge: the eager-resend protocol delivers the
    # same total message volume (pending values retransmit every round
    # until digest-acked) over cheaper rounds — measured 2.85M msgs/s vs
    # 1.68M at 4 lanes on a v5e chip
    per_nb = int(os.environ.get("BENCH_GOSSIP", 1))
    program = get_program("broadcast",
                          {"topology": "grid", "max_values": V,
                           "gossip_per_neighbor": per_nb,
                           "latency": {"mean": 0},
                           "eager_resend": eager},
                          nodes)
    cfg = T.NetConfig(n_nodes=N, n_clients=1, pool_cap=pool_cap,
                      inbox_cap=program.inbox_cap, client_cap=0)
    run_fn = make_run_fn(program, cfg)

    # Injection plan: V broadcast values, one every other round, spread
    # across the grid by a Fibonacci-hash stride.
    rr = np.arange(R)
    inj_round = (rr % 2 == 0) & (rr // 2 < V)
    value = (rr // 2) % V
    dest = (value.astype(np.int64) * 2654435761) % N
    plan = T.Msgs.empty((R, 1)).replace(
        valid=jnp.asarray(inj_round[:, None]),
        src=jnp.full((R, 1), N, T.I32),
        dest=jnp.asarray(dest.astype(np.int32)[:, None]),
        type=jnp.full((R, 1), T_BCAST, T.I32),
        a=jnp.asarray(value.astype(np.int32)[:, None]))
    chunks = jax.tree.map(
        lambda f: f.reshape((R // chunk, chunk) + f.shape[1:]), plan)

    dev = jax.devices()[0]
    print(f"bench: {N} nodes, {V} values, {R} rounds ({chunk}/dispatch), "
          f"pool {pool_cap}, device {dev.device_kind}", file=sys.stderr)

    def timed_runs(program_x, run_fn_x, label):
        """Compile+first run, then a timed run on fresh state. Returns
        (stats, converged, wall_s)."""
        def run(seed):
            sim = make_sim(program_x, cfg, seed=seed)
            for i in range(R // chunk):
                sim, _counts = run_fn_x(
                    sim, jax.tree.map(lambda f: f[i], chunks))
            # device_get forces actual remote completion;
            # block_until_ready alone does not synchronize through the
            # axon tunnel
            assert int(jax.device_get(sim.net.round)) == R
            return sim

        t0 = time.perf_counter()
        run(seed=0)
        print(f"bench{label}: compile+first run "
              f"{time.perf_counter()-t0:.1f}s", file=sys.stderr)
        t0 = time.perf_counter()
        sim2 = run(seed=1)
        dt = time.perf_counter() - t0
        st = T.stats_dict(sim2.net)
        seen = np.asarray(jax.device_get(sim2.nodes["seen"][:, :V]))
        return st, bool(seen.all()), dt

    st, converged, dt = timed_runs(program, run_fn, "")
    msgs = st["recv_all"]
    rate = msgs / dt

    record = {
        "metric": "broadcast_sim_msgs_per_sec_100k_nodes"
        if N == 100_000 else f"broadcast_sim_msgs_per_sec_{N}_nodes",
        "value": round(rate, 1),
        "unit": "msgs/sec",
        "vs_baseline": round(rate / 1e6, 4),
        "nodes": N, "values": V, "rounds": R,
        "wall_s": round(dt, 3),
        "messages_delivered": int(msgs),
        "converged": converged,
        "eager_resend": eager,
        "dropped_overflow": st["dropped_overflow"],
    }

    # the efficient (send-once-plus-retry) protocol is the interactive
    # default — the number a user actually gets — so IT is the headline
    # `value`; the eager-resend flood stays in the record as the stress
    # figure (`eager_msgs_per_sec`). Both beat the 1M north star.
    if eager and os.environ.get("BENCH_EFFICIENT", "1") == "1":
        program_eff = get_program(
            "broadcast",
            {"topology": "grid", "max_values": V,
             "gossip_per_neighbor": per_nb, "latency": {"mean": 0},
             "eager_resend": False}, nodes)
        st_e, conv_e, dt_e = timed_runs(
            program_eff, make_run_fn(program_eff, cfg), "[efficient]")
        record["value"] = round(st_e["recv_all"] / dt_e, 1)
        record["vs_baseline"] = round(st_e["recv_all"] / dt_e / 1e6, 4)
        record["eager_resend"] = False
        record["eager_msgs_per_sec"] = round(rate, 1)
        record["eager_messages_delivered"] = int(msgs)
        record["eager_wall_s"] = round(dt, 3)
        record["messages_delivered"] = int(st_e["recv_all"])
        record["wall_s"] = round(dt_e, 3)
        record["converged"] = conv_e
        record["eager_converged"] = converged
        record["dropped_overflow"] = st_e["dropped_overflow"]
        record["eager_dropped_overflow"] = st["dropped_overflow"]

    # checker-graded run at the same scale: real history, stock
    # BroadcastChecker (the north star's "passing the stock checker")
    graded = None
    if os.environ.get("BENCH_GRADED", "1") == "1":
        from maelstrom_tpu.bench_graded import run_graded
        out_dir = os.environ.get(
            "BENCH_GRADED_DIR",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "artifacts", f"bench-graded-{N}"))
        graded = run_graded(N, V, chunk=chunk, pool_cap=pool_cap,
                            out_dir=out_dir)
        record["graded"] = {k: v for k, v in graded.items()
                            if k != "checker"}
        record["graded"]["stable_latencies_ms"] = \
            graded["checker"]["stable-latencies"]

    print(json.dumps(record))
    # a non-converged, lossy, or checker-failed run is not a valid
    # benchmark: fail loudly (after emitting the JSON record)
    if not record["converged"] or record["dropped_overflow"]:
        sys.exit(1)
    if (record.get("eager_converged") is False
            or record.get("eager_dropped_overflow")):
        sys.exit(1)
    if graded is not None and graded["checker_valid"] is not True:
        sys.exit(1)


if __name__ == "__main__":
    main()
