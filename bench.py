#!/usr/bin/env python
"""Headline benchmark: simulated message throughput for the broadcast
workload at 100k nodes on one chip (BASELINE.json north star: >= 1M
simulated msgs/sec, converged under the broadcast semantics).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "msgs/sec", "vs_baseline": N/1e6, ...}

Config via env: BENCH_NODES, BENCH_VALUES, BENCH_ROUNDS, BENCH_POOL.
Runs on whatever JAX's default backend is (the real TPU under the driver);
the whole R-round simulation executes as one lax.scan dispatch, so host
latency does not pollute the measurement. The first call compiles (excluded
from timing); the timed call reuses the cached executable on fresh state.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# persist compiled executables across bench invocations (the box — and
# this directory — survives between rounds, though the cache blobs stay
# uncommitted): a recapture after a tunnel outage then costs seconds of
# compile, not ~70 s per attempt inside a flaky window
_CACHE_DEFAULT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "artifacts", "xla-cache")
if os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                         _CACHE_DEFAULT) == _CACHE_DEFAULT:
    # only materialize OUR default — an operator override (possibly a
    # gs:// remote cache) passes through untouched
    os.makedirs(_CACHE_DEFAULT, exist_ok=True)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")


_ENV_ERROR_MARKS = (
    "Unable to initialize backend", "UNAVAILABLE", "DEADLINE_EXCEEDED",
    "Socket closed", "failed to connect", "Connection reset",
)


def _is_env_error(exc: BaseException) -> bool:
    """True when the failure is the tunneled TPU backend being down, not
    a bug in the benchmark (r3 lesson: one transient backend-init failure
    lost the whole round's artifact)."""
    text = f"{type(exc).__name__}: {exc}"
    return any(m in text for m in _ENV_ERROR_MARKS)


def _probe_backend(timeout_s: float) -> str | None:
    """Initializes the default JAX backend in a SUBPROCESS with a hard
    wall-clock bound and reports WHICH platform materialized. The r5
    lesson: a backend init against a dead TPU tunnel can hang for tens
    of minutes inside this process — no retry loop can bound that — and
    the whole bench then dies to the driver's timeout (rc=124) without
    ever emitting its JSON record. A subprocess is killable; this
    process stays clean to fall back. Returning the platform name (not
    just success) matters because a FAST accelerator failure makes jax
    auto-fall-back to cpu inside the probe: that "success" must still
    trigger the shrunk-workload CPU defaults, or the full-size config
    runs on CPU for hours — rc=124 by another route."""
    import subprocess
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        print(f"bench: backend probe timed out after {timeout_s:.0f}s",
              file=sys.stderr)
        return None
    if r.returncode != 0:
        print(f"bench: backend probe failed: {(r.stderr or '')[-500:]}",
              file=sys.stderr)
        return None
    out = (r.stdout or "").strip()
    return out.splitlines()[-1] if out else None


# shrunk workload defaults for the CPU fallback: the point is a
# parseable result line in minutes, not a headline number. Explicit
# BENCH_* env settings always win (setdefault).
_CPU_FALLBACK_DEFAULTS = {
    # 400 rounds: injections end at round 128 and the 64x64 grid flood
    # needs ~2 grid diameters to converge — the convergence gate must
    # still hold on the smoke, or every fallback exits nonzero
    "BENCH_NODES": "4096", "BENCH_ROUNDS": "400", "BENCH_GRADED": "0",
    "BENCH_EFFICIENT": "0", "BENCH_RAFT_CLUSTERS": "256",
    "BENCH_RAFT_GRADED": "0",
    "BENCH_STREAM_TIME_LIMIT": "5", "BENCH_STREAM_RATE": "25",
    # batched-broadcast comparison: the speedup is message economics
    # (shape-identical per-round work), so shrunk sizes keep the ratio
    # meaningful while the wall time stays in minutes
    "BENCH_BB_NODES": "1024", "BENCH_BB_VALUES": "256",
}


def _fall_back_to_cpu(reason: str):
    """Points this process at the CPU backend with a shrunk workload and
    marks the eventual record as a fallback result."""
    print(f"bench: falling back to JAX_PLATFORMS=cpu ({reason})",
          file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["BENCH_FALLBACK"] = reason
    for k, v in _CPU_FALLBACK_DEFAULTS.items():
        os.environ.setdefault(k, v)
    from maelstrom_tpu.util import honor_jax_platforms
    honor_jax_platforms()


def _fallback_meta() -> dict:
    """Record fields describing platform + fallback state; merged into
    every emitted JSON record so a CPU-fallback number can never be
    mistaken for a TPU headline."""
    meta = {}
    try:
        import jax
        meta["platform"] = jax.default_backend()
    except Exception:
        pass
    if os.environ.get("BENCH_FALLBACK"):
        meta["fallback"] = os.environ["BENCH_FALLBACK"]
    return meta


def predicted_block(program, cfg, *, fleet=None,
                    measured_rounds_per_sec=None,
                    msgs_per_round=None,
                    rounds_per_dispatch=1) -> dict | None:
    """Static cost-model prediction for a bench's own program/config
    (doc/analyze.md "predicted vs measured"): traces the per-round step
    abstractly — no arrays materialize, 100k-node shapes trace in
    milliseconds — and returns the roofline block with the
    predicted/measured round-rate ratio. The model predicts ROUND rate;
    message density is workload semantics, so predicted msgs/s uses the
    run's OWN msgs-per-round. Best-effort: any failure returns None — a
    bench must never die on its own self-report."""
    try:
        from maelstrom_tpu.analyze.cost_model import (predict_round,
                                                      resolve_profile)
        prof = resolve_profile(None)
        rec = predict_round(program, cfg, fleet=fleet, profile=prof,
                            msgs_per_round=msgs_per_round,
                            rounds_per_dispatch=rounds_per_dispatch)
        pred = rec["predicted"]
        out = {
            "profile": prof.name,
            "rounds_per_sec": pred["rounds_per_sec"],
            "msgs_per_sec": pred["msgs_per_sec"],
            "round_s": pred["round_s"],
            "flops_per_round": rec["flops"],
            "hbm_bytes_per_round": rec["hbm_bytes_read"]
            + rec["hbm_bytes_written"],
        }
        if measured_rounds_per_sec:
            m = float(measured_rounds_per_sec)
            out["measured_rounds_per_sec"] = round(m, 3)
            out["predicted_vs_measured"] = round(
                pred["rounds_per_sec"] / m, 3)
        return out
    except Exception as e:       # pragma: no cover - depends on env
        print(f"bench: cost prediction skipped: {e!r}", file=sys.stderr)
        return None


def predicted_for_test(opts: dict, wall_s: float, *, msgs=None,
                       fleet=None) -> dict | None:
    """`predicted_block` for a `core.run`-driven bench: rebuilds the
    run's program + NetConfig the way TpuRunner does (node spec from
    the ordering axis, pool/inbox/client-lane defaults) and uses the
    virtual-time round count (time_limit / ms_per_round) as the
    measured basis. Best-effort, returns None on any failure."""
    try:
        from maelstrom_tpu import core
        from maelstrom_tpu.net import tpu as T
        from maelstrom_tpu.nodes import get_program
        merged = {**core.DEFAULTS, **opts}
        if merged.get("ordering"):
            merged["node"] = "tpu:ordered"
        nodes = core.parse_nodes(merged)
        spec = str(merged["node"]).split(":", 1)[1]
        conc = int(merged.get("concurrency") or len(nodes))
        program = get_program(spec, merged, nodes)
        n = len(nodes)
        if getattr(program, "is_edge", False):
            default_pool = max(8 * conc, 64)
        else:
            default_pool = max(4096, 4 * n * program.outbox_cap)
        cfg = T.NetConfig(
            n_nodes=n, n_clients=conc,
            pool_cap=int(merged.get("pool_cap") or default_pool),
            inbox_cap=program.inbox_cap,
            client_cap=max(2 * conc, 8),
            unit_words=tuple(getattr(program, "unit_words", ()) or ()))
        ms_per_round = float(merged.get("ms_per_round") or 1.0)
        rounds = float(merged["time_limit"]) * 1000.0 / ms_per_round
        return predicted_block(
            program, cfg, fleet=fleet,
            measured_rounds_per_sec=rounds / wall_s if wall_s else None,
            msgs_per_round=(msgs / rounds) if msgs and rounds else None)
    except Exception as e:       # pragma: no cover - depends on env
        print(f"bench: cost prediction skipped: {e!r}", file=sys.stderr)
        return None


def run_with_env_retry(fn, attempts=None, backoff_s=None,
                       metric="broadcast_sim_msgs_per_sec_100k_nodes",
                       unit="msgs/sec"):
    """Run `fn` with a BOUNDED retry loop: on an environmental
    (backend-unavailable) failure, clear the half-initialized backend and
    retry up to `attempts` times (BENCH_ATTEMPTS, default 2) with short
    `backoff_s` sleeps (BENCH_BACKOFF_S, default 20). If the backend
    never comes up, fall back to JAX_PLATFORMS=cpu once so the round
    still produces a real (marked) measurement; only when even CPU fails
    emit an "env_unavailable": true record — machine-distinguishable
    from a regression — and exit 3. Non-environmental errors propagate
    (main() wraps them in an error record). A parseable JSON line is
    emitted on every path."""
    attempts = attempts or int(os.environ.get("BENCH_ATTEMPTS", 2))
    backoff_s = backoff_s if backoff_s is not None else float(
        os.environ.get("BENCH_BACKOFF_S", 20))
    last = None
    tried_cpu = os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
    i = 0
    while i < attempts:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - filtered by _is_env_error
            if not _is_env_error(e):
                raise
            last = e
            print(f"bench: backend unavailable (attempt {i + 1}/"
                  f"{attempts}): {e}", file=sys.stderr)
            try:
                import jax._src.xla_bridge as xb
                xb._clear_backends()
            except Exception as ce:  # private API — may vanish in a
                #                      jax upgrade; make that visible
                print(f"bench: backend reset unavailable "
                      f"({type(ce).__name__}: {ce}) — retrying against "
                      f"the existing backend state", file=sys.stderr)
            i += 1
            if i >= attempts and not tried_cpu:
                # last resort before giving up: one CPU pass
                _fall_back_to_cpu(f"backend unavailable after "
                                  f"{attempts} attempts")
                tried_cpu = True
                attempts += 1
                continue
            if i < attempts:
                time.sleep(backoff_s)
    print(json.dumps({
        "metric": metric,
        "value": None, "unit": unit, "vs_baseline": None,
        "env_unavailable": True,
        "error": f"{type(last).__name__}: {last}",
        "attempts": attempts,
        **_fallback_meta(),
    }))
    sys.exit(3)


def elle_synthetic(elle_ops):
    """The checker bench's synthetic list-append transaction set:
    per-key serial version chains plus random prefix reads, ~elle_ops
    micro-ops total. Key count scales DOWN with tiny elle_ops so the
    version-construction floor (2 appends per key) never eats the whole
    budget — small sizes keep a read-bearing, multi-version workload
    instead of degenerating to appends-only single-version keys.
    Returns (txns, longest, appender, micro_ops)."""
    ekeys = min(64, max(1, elle_ops // 10))
    versions_per_key = max(2, elle_ops // (5 * ekeys))
    rng = np.random.RandomState(7)
    txns, longest, appender = [], {}, {}
    micro_ops = 0
    for ki in range(ekeys):
        kk = repr(ki)
        order = []
        for vi in range(versions_per_key):
            vv = repr(ki * versions_per_key + vi)
            tid = len(txns)
            txns.append({"id": tid, "ok": True, "inv": micro_ops,
                         "ret": micro_ops + 1,
                         "micro": [["append", ki,
                                    ki * versions_per_key + vi]]})
            appender[(kk, vv)] = tid
            order.append(vv)
            micro_ops += 1
        longest[kk] = order
    # reads fill whatever the version floor left of the budget
    n_reads = max(0, elle_ops - micro_ops)
    read_keys = rng.randint(0, ekeys, n_reads)
    read_lens = rng.randint(0, versions_per_key + 1, n_reads)
    for ki, ln in zip(read_keys.tolist(), read_lens.tolist()):
        tid = len(txns)
        txns.append({"id": tid, "ok": True, "inv": micro_ops,
                     "ret": micro_ops + 1,
                     "micro": [["r", ki,
                                list(range(ki * versions_per_key,
                                           ki * versions_per_key
                                           + ln))]]})
        micro_ops += 1
    return txns, longest, appender, micro_ops


def bench_elle_device_record(txns, longest, appender, micro_ops,
                             py_s, ev) -> dict:
    """The device-resident edge build + cycle screen
    (checkers/elle_device.py, doc/perf.md "device-resident grading")
    against the pure-Python baseline time `py_s`:

      - flatten_s: the one-shot host columnarization of the read table
        (on overlapped production runs the stream observer builds this
        incrementally, concurrent with device compute);
      - table_s: the per-key version-table merge + gather positions
        (host numpy);
      - build_s: the jitted edge construction, post-compile, timed to
        `block_until_ready` — the at-check cost when the pipeline
        pre-fed the columns;
      - screen_s: the jitted data-stage cycle screen (this synthetic's
        stale prefix reads make it realtime-CYCLIC by design, so only
        the data stage is meaningful here; the decided-fraction
        fixtures below exercise the realtime stage on valid shapes).

    `speedup` (the acceptance figure) = python_s / build_s;
    `speedup_total` = python_s / (flatten + table + build), the honest
    one-shot post-hoc number. The edge set is asserted equal to the
    vectorized build (`match`)."""
    from maelstrom_tpu.checkers import elle_device as ed
    if not ed.available():
        return {"available": False}
    import jax

    t0 = time.perf_counter()
    cols = ed.build_columns(txns)
    flatten_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    writers, slot_key, slot_idx, offsets, lens, key_idx = \
        ed._writer_table(longest, appender, repr)
    tid, n_, wr_pos, rw_pos = ed.read_positions(cols, key_idx, offsets,
                                                lens, repr)
    # the production assembly (ed.device_args — the same padding +
    # index scatter screen_arrays dispatches through), with no rt
    # inputs: this synthetic's stale prefix reads make it
    # realtime-cyclic by design, so only the data stage is timed
    no_rt = np.zeros(0, np.int64)
    eargs, sargs, tp, have_rt = ed.device_args(
        writers, slot_key, slot_idx, tid, n_, wr_pos, rw_pos, no_rt,
        no_rt, len(txns))
    table_s = time.perf_counter() - t0

    fns = ed._fns()
    jax.block_until_ready(fns["edges"](*eargs))     # compile
    t0 = time.perf_counter()
    earrs = fns["edges"](*eargs)
    jax.block_until_ready(earrs)
    build_s = time.perf_counter() - t0

    jax.block_until_ready(fns["screen"](*sargs, n_txns_pad=tp,
                                        do_rt=have_rt))   # compile
    t0 = time.perf_counter()
    data_ok, _full, it_a, _it_b = jax.device_get(
        fns["screen"](*sargs, n_txns_pad=tp, do_rt=have_rt))
    screen_s = time.perf_counter() - t0

    es = ed.DeviceElle(earrs, data_ok, False,
                       (int(it_a), 0), {}).edge_set()
    total_s = flatten_s + table_s + build_s
    rec = {
        "flatten_s": round(flatten_s, 4),
        "table_s": round(table_s, 4),
        "build_s": round(build_s, 4),
        "screen_s": round(screen_s, 4),
        "total_s": round(total_s, 4),
        "build_ops_per_s": round(micro_ops / max(build_s, 1e-9), 1),
        "match": es == ev,
        "speedup": round(py_s / max(build_s, 1e-9), 2),
        "speedup_total": round(py_s / max(total_s, 1e-9), 2),
        "screen_data_decided": bool(data_ok),
        "screen_iters": int(it_a),
    }

    # screen decided-fraction: valid (acyclic) concurrent histories
    # from the shared generator — the screen must certify >= 90% of
    # them end to end (realtime stage included), skipping Tarjan
    from maelstrom_tpu.checkers.elle import (_fail_appends, _txn_ops,
                                             analyze_txns)
    from maelstrom_tpu.testing.histories import random_append_history
    n_fix = int(os.environ.get("BENCH_CHECKER_SCREEN_FIXTURES", 12))
    decided = 0
    for seed in range(n_fix):
        h = random_append_history(seed, n_txn=150)
        rep = {}
        analyze_txns(_txn_ops(h), _fail_appends(h), device="on",
                     report=rep)
        if rep.get("screen", {}).get("realtime") == "acyclic":
            decided += 1
    rec["screen_fixtures"] = {
        "histories": n_fix, "decided": decided,
        "decided_fraction": round(decided / max(n_fix, 1), 3),
    }
    return rec


def bench_checkers_record(n_rows=None, elle_ops=None) -> dict:
    """Checker-throughput section: the analysis pipeline's hot paths on
    synthetic histories, each against its pure-Python baseline, so
    checker perf rides the BENCH_*.json trajectory next to simulation
    msgs/s.

      - register: a 1M-row lin-kv history through
        LinearizableRegisterChecker — columnar partition + vectorized
        screen vs. the sequential pairs()+WGL path (opts no_fast)
      - elle: ww/wr/rw dependency-edge construction on a ~1M-micro-op
        list-append transaction set — sorted-index-array build vs. the
        nested-loop build
      - elle.device: the SAME edge set built by the jitted device
        constructor plus the on-device cycle screen
        (doc/perf.md "device-resident grading"), with a
        screen-decided-fraction sweep over valid synthetic histories

    The register/elle halves are pure host/numpy (identical on the CPU
    fallback); the device block runs on whatever backend jax has. All
    halves assert verdict/edge equality; a mismatch marks the record
    invalid."""
    from maelstrom_tpu.checkers.elle import (_edges_python,
                                             _edges_vectorized)
    from maelstrom_tpu.checkers.linearizable import \
        LinearizableRegisterChecker
    from maelstrom_tpu.history import History

    n_rows = n_rows or int(os.environ.get("BENCH_CHECKER_OPS", 1_000_000))
    n_rows -= n_rows % 2
    n_ops = n_rows // 2
    keys = int(os.environ.get("BENCH_CHECKER_KEYS", 128))

    # synthetic sequential lin-kv history: one worker, every 4th op a
    # write, reads observe the running per-key state (valid by
    # construction; the screen decides every key without WGL)
    h = History()
    state = [None] * keys
    types, fs, vals, procs, times = [], [], [], [], []
    t = 0
    for i in range(n_ops):
        k = i % keys
        if i % 4 == 0:
            f, v = "write", i % 7
            state[k] = v
        else:
            f, v = "read", state[k]
        types += ["invoke", "ok"]
        fs += [f, f]
        vals += [[k, v], [k, v]]
        procs += [0, 0]
        times += [t, t + 1]
        t += 2
    h.extend_columns(types, fs, vals, procs, times)

    c = LinearizableRegisterChecker()
    t0 = time.perf_counter()
    fast = c.check({}, h)
    fast_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    base = c.check({}, h, {"no_fast": True})
    base_s = time.perf_counter() - t0
    register = {
        "history_rows": n_rows, "ops": n_ops, "keys": keys,
        "valid": fast["valid"], "verdicts_match": fast == base,
        "fast_s": round(fast_s, 4),
        "fast_ops_per_s": round(n_ops / fast_s, 1),
        "baseline_s": round(base_s, 4),
        "baseline_ops_per_s": round(n_ops / base_s, 1),
        "speedup": round(base_s / fast_s, 2),
    }

    # elle: synthetic append/read transaction set -> edge build only
    elle_ops = elle_ops or int(
        os.environ.get("BENCH_CHECKER_ELLE_OPS", 1_000_000))
    txns, longest, appender, micro_ops = elle_synthetic(elle_ops)
    t0 = time.perf_counter()
    ev = _edges_vectorized(txns, longest, appender)
    vec_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    ep = _edges_python(txns, longest, appender)
    py_s = time.perf_counter() - t0
    elle = {
        "micro_ops": micro_ops, "keys": len(longest),
        "edges": len(ev), "match": ev == ep,
        "vectorized_s": round(vec_s, 4),
        "vectorized_ops_per_s": round(micro_ops / vec_s, 1),
        "python_s": round(py_s, 4),
        "python_ops_per_s": round(micro_ops / py_s, 1),
        "speedup": round(py_s / vec_s, 2),
    }

    # device path (BENCH_CHECKER_DEVICE=0 to skip): jitted edge build
    # + on-device cycle screen vs the same python baseline
    device = None
    if os.environ.get("BENCH_CHECKER_DEVICE", "1") == "1":
        device = bench_elle_device_record(txns, longest, appender,
                                          micro_ops, py_s, ev)
        elle["device"] = device

    dev_ok = (device is None or not device.get("available", True)
              or (device["match"]
                  and device["screen_fixtures"]["decided_fraction"]
                  >= 0.9))
    return {"register": register, "elle": elle,
            "valid": bool(register["verdicts_match"] and elle["match"]
                          and register["valid"] is True and dev_ok)}


def bench_raft_clusters():
    """Secondary benchmark: 10k independent 5-node raft clusters advance
    under one vmap (BASELINE config 4). Metric: cluster-rounds/sec —
    simulated raft rounds x clusters per wall second — plus a leader-
    election sanity check."""
    import jax

    from maelstrom_tpu.net import tpu as T
    from maelstrom_tpu.nodes import get_program
    from maelstrom_tpu.parallel import make_cluster_round_fn, \
        make_cluster_sims

    n = int(os.environ.get("BENCH_RAFT_NODES", 5))
    clusters = int(os.environ.get("BENCH_RAFT_CLUSTERS", 10_000))
    R = int(os.environ.get("BENCH_ROUNDS", 300))
    chunk = min(int(os.environ.get("BENCH_CHUNK", 100)), R)

    nodes = [f"n{i}" for i in range(n)]
    program = get_program("lin-kv", {"latency": {"mean": 0}}, nodes)
    cfg = T.NetConfig(n_nodes=n, n_clients=1, pool_cap=64,
                      inbox_cap=program.inbox_cap, client_cap=4)
    round_fn = make_cluster_round_fn(program, cfg)
    # donated carry (BENCH_DONATE=0 to compare): the fleet state tree is
    # reused in place across chunked dispatches instead of reallocated.
    # donation_enabled() keeps it off on the CPU backend (see sim.py)
    from maelstrom_tpu.sim import donation_enabled
    donate = (os.environ.get("BENCH_DONATE", "1") == "1"
              and donation_enabled())
    scan = jax.jit(lambda sims, _: jax.lax.scan(
        lambda s, x: (round_fn(s, T.Msgs.empty((clusters, 1)))[0], None),
        sims, None, length=chunk)[0],
        donate_argnums=(0,) if donate else ())

    def run(sims):
        for _ in range(R // chunk):
            sims = scan(sims, None)
        assert int(jax.device_get(sims.net.round[0])) == \
            (R // chunk) * chunk
        return sims

    print(f"bench[raft]: {clusters} clusters x {n} nodes, {R} rounds",
          file=sys.stderr)
    sims0 = make_cluster_sims(program, cfg, clusters, seed=0)
    sims1 = make_cluster_sims(program, cfg, clusters, seed=1)
    t0 = time.perf_counter()
    run(sims0)
    print(f"bench[raft]: compile+first run {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)
    t0 = time.perf_counter()
    sims = run(sims1)              # sims built outside the timed window
    dt = time.perf_counter() - t0

    import numpy as np
    roles = np.asarray(jax.device_get(sims.nodes["role"]))
    one_leader = float(((roles == 2).sum(axis=1) == 1).mean())
    rounds_done = (R // chunk) * chunk
    rate = rounds_done * clusters / dt
    record = {
        "metric": "raft_cluster_rounds_per_sec_10k_clusters",
        "value": round(rate, 1), "unit": "cluster-rounds/sec",
        "vs_baseline": round(rate / 1e6, 4),
        "clusters": clusters, "nodes_per_cluster": n,
        "rounds": rounds_done, "wall_s": round(dt, 3),
        "clusters_with_one_leader": one_leader,
        "donated_carry": donate,
        **_fallback_meta(),
    }

    # grading half: real contending client traffic into a sampled subset
    # of the same-size vmapped fleet, every sampled history graded by
    # the stock WGL linearizability checker — with a partition nemesis
    # ACTIVE during the graded window (every cluster gets an independent
    # majority/minority split, healed before each worker's final read)
    if os.environ.get("BENCH_RAFT_GRADED", "1") == "1":
        from maelstrom_tpu.bench_raft_graded import run_raft_graded
        g = run_raft_graded(
            n_clusters=clusters, n=n,
            sample=int(os.environ.get("BENCH_RAFT_SAMPLE", 512)),
            ops_per_client=int(os.environ.get("BENCH_RAFT_OPS", 50)),
            partition_at=int(os.environ.get("BENCH_RAFT_PART_AT", 20)),
            partition_chunks=int(
                os.environ.get("BENCH_RAFT_PART_CHUNKS", 30)),
            max_chunks=800,
            seed=3)
        record["graded"] = g
        record["sampled_clusters"] = g["sampled_clusters"]
        record["all_linearizable"] = g["all_linearizable"]
    print(json.dumps(record))
    if record.get("all_linearizable") is False:
        sys.exit(1)
    if one_leader < 1.0:
        sys.exit(1)


def bench_fleet_record(sizes=None) -> dict:
    """Fleet-execution throughput (`--fleet N`, ISSUE 6): the SAME
    per-cluster broadcast workload advanced at fleet sizes 1/8/64/512
    inside ONE vmapped compiled scan (`sim.make_fleet_scan_fn` — the
    exact dispatch every fleet wave runs). Two metrics per size:

      - clusters/sec: campaign throughput — clusters completing the
        full R-round workload per wall second (the fleet lever turns
        rounds/sec into clusters/sec);
      - aggregate msgs/sec: messages simulated across the whole fleet
        per wall second.

    The fleet=64 vs fleet=1 aggregate ratio is the acceptance figure:
    >= 8x on hardware with idle parallel capacity (a TPU chip, or a
    many-core host). The per-cluster round is REAL compute — batching
    only wins what the hardware has spare — so the record carries
    `host_cpus`/`devices` context: on a 2-core CPU-fallback box the
    ratio honestly tops out near 3x (measured; op-dispatch overhead
    fully amortized, the rest is arithmetic the one core must still
    do), while the idle systolic array is exactly what the TPU
    recapture (run_tpu_recapture.sh) exists to measure. Every size must
    converge (all values seen on every node of every cluster) and drop
    nothing — a non-converged size invalidates the record.

    `BENCH_FLEET_MESH=dp,sp` additionally shards the cluster axis over
    dp (`parallel.fleet_scan_shardings`, requires dp*sp visible
    devices and every size % dp == 0) — the `--fleet N --mesh dp,sp`
    production layout."""
    import jax
    import jax.numpy as jnp

    from maelstrom_tpu import parallel
    from maelstrom_tpu.net import tpu as T
    from maelstrom_tpu.nodes import get_program
    from maelstrom_tpu.nodes.broadcast import T_BCAST
    from maelstrom_tpu.parallel import make_fleet_sims
    from maelstrom_tpu.sim import (dealias, donation_enabled,
                                   make_fleet_scan_fn)

    if sizes is None:
        sizes = [int(s) for s in os.environ.get(
            "BENCH_FLEET_SIZES", "1,8,64,512").split(",") if s.strip()]
    # 5-node clusters: the canonical Jepsen test-cluster size (the raft
    # fleet bench uses the same), and the shape campaigns actually sweep
    n = int(os.environ.get("BENCH_FLEET_NODES", 5))
    V = int(os.environ.get("BENCH_FLEET_VALUES", 8))    # dispatches
    chunk = int(os.environ.get("BENCH_FLEET_CHUNK", 64))  # rounds each
    pool_cap = int(os.environ.get("BENCH_FLEET_POOL", 64))
    mesh_spec = os.environ.get("BENCH_FLEET_MESH")
    # dp>1 x sp>1 meshes are fine: make_fleet_scan_fn runs the body
    # manual under shard_map on mixed meshes (sim.fleet_shard_map)
    mesh = parallel.mesh_from_spec(mesh_spec) if mesh_spec else None
    donate = (os.environ.get("BENCH_DONATE", "1") == "1"
              and donation_enabled())

    nodes = [f"n{i}" for i in range(n)]
    program = get_program("broadcast",
                          {"topology": "grid", "max_values": V,
                           "latency": {"mean": 0},
                           "eager_resend": True}, nodes)
    cfg = T.NetConfig(n_nodes=n, n_clients=1, pool_cap=pool_cap,
                      inbox_cap=program.inbox_cap, client_cap=0)
    R = V * chunk

    rows = []
    for F in sizes:
        sh = None
        if mesh is not None:
            if F % mesh.shape["dp"]:
                raise ValueError(f"BENCH_FLEET_MESH={mesh_spec}: fleet "
                                 f"size {F} % dp != 0")
            # shardings only need tree structure + shapes: derive them
            # from abstract values instead of materializing the largest
            # fleet's device tree twice
            ex_sim = jax.eval_shape(
                lambda: make_fleet_sims(program, cfg, seeds=range(F)))
            ex_inj = jax.eval_shape(lambda: jax.tree.map(
                lambda a: jnp.broadcast_to(a, (F,) + a.shape),
                T.Msgs.empty(1)))
            sh = parallel.fleet_scan_shardings(mesh, ex_sim, ex_inj)
        fleet_fn = make_fleet_scan_fn(program, cfg, donate=donate,
                                      shardings=sh)
        kmax = jnp.full((F,), chunk, jnp.int32)
        hold = jnp.zeros((F,), bool)        # never stop-on-reply
        active = jnp.ones((F,), bool)
        injects = []
        for d in range(V):
            # one fresh broadcast value per cluster per dispatch, dest
            # spread per (cluster, value) by the Fibonacci-hash stride
            dest = (np.arange(F, dtype=np.int64) * V + d) \
                * 2654435761 % n
            injects.append(T.Msgs.empty((F, 1)).replace(
                valid=jnp.ones((F, 1), bool),
                src=jnp.full((F, 1), n, T.I32),
                dest=jnp.asarray(dest.astype(np.int32)[:, None]),
                type=jnp.full((F, 1), T_BCAST, T.I32),
                a=jnp.full((F, 1), d, T.I32)))

        def run(seed0, F=F, fleet_fn=fleet_fn, kmax=kmax, hold=hold,
                active=active, injects=injects, sh=sh):
            sim = make_fleet_sims(program, cfg,
                                  seeds=range(seed0, seed0 + F))
            if donate:
                sim = dealias(sim)
            if sh is not None:
                sim = jax.device_put(sim, sh[0])
            for inj in injects:
                sim, _cm, _k = fleet_fn(sim, inj, kmax, hold, active)
            # device_get forces actual remote completion (see
            # _main_broadcast)
            assert int(jax.device_get(sim.net.round[0])) == R
            return sim

        t0 = time.perf_counter()
        run(0)
        print(f"bench[fleet={F}]: compile+first run "
              f"{time.perf_counter()-t0:.1f}s", file=sys.stderr)
        t0 = time.perf_counter()
        sim = run(F)
        dt = time.perf_counter() - t0
        st = T.stats_dict(sim.net)          # sums over the fleet axis
        seen = np.asarray(jax.device_get(sim.nodes["seen"][:, :, :V]))
        rows.append({
            "fleet": F, "wall_s": round(dt, 3),
            "rounds_per_cluster": R,
            "messages_delivered": int(st["recv_all"]),
            "agg_msgs_per_sec": round(st["recv_all"] / dt, 1),
            "clusters_per_sec": round(F / dt, 3),
            "converged": bool(seen.all()),
            "dropped_overflow": st["dropped_overflow"],
            "predicted": predicted_block(
                program, cfg, fleet=F,
                measured_rounds_per_sec=R / dt,
                msgs_per_round=st["recv_all"] / R,
                rounds_per_dispatch=chunk),
        })
        print(f"bench[fleet={F}]: {rows[-1]['agg_msgs_per_sec']:.0f} "
              f"agg msgs/s, {rows[-1]['clusters_per_sec']:.2f} "
              f"clusters/s", file=sys.stderr)

    base = next((r for r in rows if r["fleet"] == 1), rows[0])
    for r in rows:
        r["agg_speedup_vs_fleet1"] = round(
            r["agg_msgs_per_sec"] / base["agg_msgs_per_sec"], 2)
    return {
        "sizes": rows,
        "nodes_per_cluster": n, "values": V,
        "rounds_per_cluster": R,
        "donated_carry": donate,
        "mesh": mesh_spec,
        # batching only wins the hardware's spare parallelism: these
        # fields keep a 2-core CPU-fallback ratio from being read as
        # the TPU number
        "host_cpus": os.cpu_count(),
        "devices": jax.device_count(),
        "valid": all(r["converged"] and not r["dropped_overflow"]
                     for r in rows),
    }


def bench_podmesh_record(fleets=None, meshes=None) -> dict:
    """Pod-scale mixed-mesh grid (ISSUE 18, doc/perf.md "pod-scale
    mixed mesh"): the END-TO-END `--fleet N --mesh dp,sp` production
    path (`core.run` -> fleet runner -> shard_map scan body on mixed
    meshes) swept over fleet {2, 8} x mesh {1,1 / 2,1 / 1,2 / 2,2}.
    Two metrics per cell:

      - agg_ops_per_vsec: completed ok client ops summed over every
        cluster per simulated second — virtual throughput, the number
        that scales with the mesh regardless of host speed;
      - agg_msgs_per_sec: messages delivered across the fleet per wall
        second (wall includes compile + per-cluster checking — an
        end-to-end figure, not a kernel figure).

    The 2,2 cells are the ones PR 2 had to reject: dp>1 x sp>1 runs
    the scan body manual under shard_map (`sim.fleet_shard_map`), and
    at fleet=8 the 8 % 4 == 0 fully-sharded `P(("dp","sp"))` fleet
    axis engages (fleet=2 exercises the dp-only replicated mode).
    Cells whose mesh needs more devices than are visible are recorded
    under `skipped`, never dropped silently — on CPU, force a 4-device
    mesh with XLA_FLAGS=--xla_force_host_platform_device_count=4. A
    2-core host splits the same two cores across every mesh shape, so
    CPU r01 wall numbers are an honesty baseline for the TPU recapture
    (run_tpu_recapture.sh step 1l), not a scaling claim."""
    import shutil
    import tempfile

    import jax

    from maelstrom_tpu import core

    if fleets is None:
        fleets = [int(x) for x in os.environ.get(
            "BENCH_PODMESH_FLEETS", "2,8").split(",") if x.strip()]
    if meshes is None:
        meshes = [m.strip() for m in os.environ.get(
            "BENCH_PODMESH_MESHES", "1,1;2,1;1,2;2,2").split(";")
            if m.strip()]
    # rate 25 (not 10): the jepsen stats rule wants >= 1 ok per op type
    # in EVERY cluster, and at 10 ops/s x 2 vsec some fleet-8 seeds
    # never complete a cas
    rate = float(os.environ.get("BENCH_PODMESH_RATE", 25.0))
    tl = float(os.environ.get("BENCH_PODMESH_TIME_LIMIT", 2.0))
    seed = int(os.environ.get("BENCH_PODMESH_SEED", 16))
    rows, skipped = [], []
    root = tempfile.mkdtemp(prefix="bench-podmesh-")
    try:
        for spec in meshes:
            dp, sp = (int(x) for x in spec.split(","))
            if dp * sp > jax.device_count():
                skipped.append({"mesh": spec, "reason":
                                f"needs {dp * sp} devices, "
                                f"{jax.device_count()} visible"})
                print(f"bench[podmesh mesh={spec}]: SKIPPED "
                      f"({skipped[-1]['reason']})", file=sys.stderr)
                continue
            for F in fleets:
                if F % dp:
                    skipped.append({"mesh": spec, "fleet": F, "reason":
                                    f"fleet {F} % dp={dp} != 0"})
                    continue
                t0 = time.perf_counter()
                res = core.run(dict(
                    store_root=root, seed=seed, workload="lin-kv",
                    node="tpu:lin-kv", node_count=3, rate=rate,
                    time_limit=tl, recovery_s=0.5, fleet=F,
                    mesh=None if spec == "1,1" else spec,
                    audit=False, journal_rows=False))
                dt = time.perf_counter() - t0
                ok = sum(c["stats"]["ok-count"] for c in res["clusters"])
                msgs = sum(c["net"]["all"]["recv-count"]
                           for c in res["clusters"])
                rows.append({
                    "fleet": F, "mesh": spec, "dp": dp, "sp": sp,
                    "ok_ops": ok,
                    "agg_ops_per_vsec": round(ok / tl, 1),
                    "messages_delivered": msgs,
                    "agg_msgs_per_sec": round(msgs / dt, 1),
                    "wall_s": round(dt, 3),
                    "valid": res["valid"] is True,
                    "predicted": predicted_for_test(
                        dict(workload="lin-kv", node="tpu:lin-kv",
                             node_count=3, time_limit=tl),
                        dt, msgs=msgs, fleet=F),
                })
                print(f"bench[podmesh fleet={F} mesh={spec}]: "
                      f"{rows[-1]['agg_msgs_per_sec']:.0f} agg msgs/s, "
                      f"{rows[-1]['agg_ops_per_vsec']:.0f} ops/vsec "
                      f"({dt:.1f}s wall), valid={rows[-1]['valid']}",
                      file=sys.stderr)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {
        "cells": rows,
        "skipped": skipped,
        "offered_rate": rate, "time_limit_s": tl, "seed": seed,
        "host_cpus": os.cpu_count(),
        "devices": jax.device_count(),
        "valid": bool(rows) and all(r["valid"] for r in rows),
    }


def _main_podmesh():
    """`BENCH_MODE=podmesh`: the fleet x mesh grid as its own artifact,
    headline `value` = aggregate msgs/sec on the biggest mixed (2,2)
    cell (falling back to the biggest cell run when no mixed mesh fit
    the visible devices)."""
    rec = bench_podmesh_record()
    cells = rec["cells"]
    mixed = [r for r in cells if r["dp"] > 1 and r["sp"] > 1]
    top = max(mixed or cells or [{}],
              key=lambda r: (r.get("fleet", 0), r.get("sp", 0)))
    record = {
        "metric": "podmesh_agg_msgs_per_sec",
        "value": top.get("agg_msgs_per_sec"),
        "unit": "msgs/sec",
        "fleet": top.get("fleet"), "top_mesh": top.get("mesh"),
        "agg_ops_per_vsec": top.get("agg_ops_per_vsec"),
        **rec,
        **_fallback_meta(),
    }
    print(json.dumps(record))
    if not rec["valid"]:
        sys.exit(1)


def bench_broadcast_batched_record() -> dict:
    """Chop Chop-grade batched atomic broadcast (ISSUE 9, doc/perf.md):
    the distilled-batch node (`nodes/broadcast_batched.py`) against the
    eager-resend gossip node at EQUAL node count, same grid, same
    zero-latency network. Both runs deliver the same V client values to
    every node; each is timed to ITS OWN convergence (all values seen
    everywhere — checked per chunk, identically for both), because the
    batching win IS finishing the same workload in fewer simulated
    messages and rounds.

    Metrics per protocol:
      - client_ops_per_sec: V client ops fully delivered per wall
        second — the Chop Chop headline (ops/s at the network limit);
      - msgs_per_sec: raw simulated messages per wall second (the
        "network limit" both protocols saturate);
      - units_per_msg: logical client-op units per network message
        (1.0 for eager by construction; the batched node's distillation
        factor, from the net's sent_units/recv_units counters).

    `speedup_client_ops` (batched over eager) is the acceptance figure:
    >= 2x on the same hardware, CPU fallback included — the per-round
    array work is shape-identical for both protocols, so the ratio is
    pure message economics, not idle-parallelism dependent (unlike the
    fleet ratio). A non-converged side invalidates the record."""
    import jax
    import jax.numpy as jnp

    from maelstrom_tpu.net import tpu as T
    from maelstrom_tpu.nodes import get_program
    from maelstrom_tpu.nodes.broadcast import T_BCAST
    from maelstrom_tpu.nodes.broadcast_batched import (T_BATCH,
                                                       range_checksum)
    from maelstrom_tpu.sim import (dealias, donation_enabled,
                                   make_run_fn, make_sim)

    N = int(os.environ.get("BENCH_BB_NODES", 4096))
    V = int(os.environ.get("BENCH_BB_VALUES", 512))
    B = int(os.environ.get("BENCH_BB_BATCH", 32))
    chunk = int(os.environ.get("BENCH_BB_CHUNK", 64))
    # generous horizon: the eager side needs ~V rounds per edge backlog
    # plus grid mixing; convergence exits early, the horizon only backs
    # the non-convergence failure mode
    max_rounds = int(os.environ.get("BENCH_BB_MAX_ROUNDS", 16 * V))
    max_rounds = max(chunk, (max_rounds // chunk) * chunk)
    pool_cap = int(os.environ.get("BENCH_BB_POOL", 4096))
    donate = (os.environ.get("BENCH_DONATE", "1") == "1"
              and donation_enabled())
    nodes = [f"n{i}" for i in range(N)]

    def measure(kind):
        opts = {"topology": "grid", "max_values": V,
                "gossip_per_neighbor": 1, "latency": {"mean": 0},
                "eager_resend": True}
        if kind == "batched":
            prog = get_program("broadcast-batched",
                               {**opts, "batch_max": B}, nodes)
            n_inj = (V + B - 1) // B
            lo = np.arange(n_inj, dtype=np.int64) * B
            n_vals = np.minimum(B, V - lo)
            a_col, b_col = lo, n_vals
            c_col = np.array([int(range_checksum(int(l), int(n)))
                              for l, n in zip(lo, n_vals)])
            t_code = T_BATCH
        else:
            prog = get_program("broadcast", opts, nodes)
            n_inj = V
            a_col = np.arange(V, dtype=np.int64)
            b_col = np.zeros(V, dtype=np.int64)
            c_col = np.zeros(V, dtype=np.int64)
            t_code = T_BCAST
        cfg = T.NetConfig(
            n_nodes=N, n_clients=1, pool_cap=pool_cap,
            inbox_cap=prog.inbox_cap, client_cap=0,
            unit_words=tuple(getattr(prog, "unit_words", ()) or ()))
        run_fn = make_run_fn(prog, cfg, donate=donate)
        # one injection per round starting at round 0, dest spread by
        # the Fibonacci-hash stride (same discipline as _main_broadcast)
        rr = np.arange(max_rounds)
        live = rr < n_inj
        j = np.minimum(rr, n_inj - 1)
        dest = (a_col[j] * 2654435761) % N
        plan = T.Msgs.empty((max_rounds, 1)).replace(
            valid=jnp.asarray(live[:, None]),
            src=jnp.full((max_rounds, 1), N, T.I32),
            dest=jnp.asarray(dest.astype(np.int32)[:, None]),
            type=jnp.full((max_rounds, 1), t_code, T.I32),
            a=jnp.asarray(a_col[j].astype(np.int32)[:, None]),
            b=jnp.asarray(b_col[j].astype(np.int32)[:, None]),
            c=jnp.asarray(c_col[j].astype(np.int32)[:, None]))
        chunks = jax.tree.map(
            lambda f: f.reshape((max_rounds // chunk, chunk)
                                + f.shape[1:]), plan)
        conv = jax.jit(lambda sim: sim.nodes["seen"][:, :V].all())

        def run(seed):
            sim = make_sim(prog, cfg, seed=seed)
            if donate:
                sim = dealias(sim)
            rounds = 0
            for i in range(max_rounds // chunk):
                sim, _ = run_fn(sim,
                                jax.tree.map(lambda f, i=i: f[i], chunks))
                rounds += chunk
                # per-chunk convergence probe: one scalar fetch, booked
                # identically for both protocols inside the timed window
                if bool(jax.device_get(conv(sim))):
                    break
            return sim, rounds

        t0 = time.perf_counter()
        run(seed=0)
        print(f"bench[batched:{kind}]: compile+first run "
              f"{time.perf_counter()-t0:.1f}s", file=sys.stderr)
        t0 = time.perf_counter()
        sim, rounds = run(seed=1)
        dt = time.perf_counter() - t0
        st = T.stats_dict(sim.net)
        seen = np.asarray(jax.device_get(sim.nodes["seen"][:, :V]))
        units = st["recv_units"] if cfg.unit_words else st["recv_all"]
        return {
            "protocol": kind,
            "rounds_to_convergence": rounds,
            "wall_s": round(dt, 3),
            "converged": bool(seen.all()),
            "client_ops": V,
            "client_ops_per_sec": round(V / dt, 1),
            "messages_delivered": int(st["recv_all"]),
            "msgs_per_sec": round(st["recv_all"] / dt, 1),
            "units_delivered": int(units),
            "units_per_msg": round(units / max(st["recv_all"], 1), 3),
            "dropped_overflow": st["dropped_overflow"],
            "predicted": predicted_block(
                prog, cfg,
                measured_rounds_per_sec=rounds / dt if dt else None,
                msgs_per_round=st["recv_all"] / max(rounds, 1),
                rounds_per_dispatch=chunk),
        }

    rows = [measure("eager"), measure("batched")]
    eager, batched = rows
    speedup = round(batched["client_ops_per_sec"]
                    / max(eager["client_ops_per_sec"], 1e-9), 2)
    for r in rows:
        print(f"bench[batched]: {r['protocol']}: "
              f"{r['client_ops_per_sec']:.1f} ops/s, "
              f"{r['msgs_per_sec']:.0f} msgs/s, "
              f"{r['rounds_to_convergence']} rounds", file=sys.stderr)
    return {
        "protocols": rows,
        "nodes": N, "values": V, "batch": B,
        "speedup_client_ops": speedup,
        "msg_reduction": round(
            eager["messages_delivered"]
            / max(batched["messages_delivered"], 1), 2),
        "donated_carry": donate,
        "host_cpus": os.cpu_count(),
        "devices": jax.device_count(),
        "valid": all(r["converged"] and not r["dropped_overflow"]
                     for r in rows),
    }


def _main_broadcast_batched():
    """`BENCH_MODE=broadcast_batched`: the batched-vs-eager record as
    its own artifact, headline `value` = the batched node's delivered
    client-ops/s, `vs_baseline` = the speedup over eager-resend at
    equal node count (the ISSUE 9 acceptance figure)."""
    bb = bench_broadcast_batched_record()
    top = next(r for r in bb["protocols"] if r["protocol"] == "batched")
    record = {
        "metric": "broadcast_batched_client_ops_per_sec",
        "value": top["client_ops_per_sec"],
        "unit": "client-ops/sec",
        "vs_baseline": bb["speedup_client_ops"],
        **bb,
        **_fallback_meta(),
    }
    print(json.dumps(record))
    if not bb["valid"]:
        sys.exit(1)


def bench_stream_record(mults=None) -> dict:
    """Open-world stream throughput (doc/streams.md): continuous-mode
    streaming kafka — consumer groups, cursor fetches, windowed
    incremental grading — driven END TO END through `core.run` at
    1x/4x/16x the base offered rate. Two numbers per rate:

      - sustained throughput: completed client ops/sec and simulated
        network msgs/sec over the whole run's wall clock (generator
        scheduling + sched-inject scan + drain + incremental grading —
        the full stream loop, not a kernel microbench);
      - max checker lag (rounds the scan head ran ahead of the windowed
        grader): bounded lag = the checker keeps up at that rate.

    Every rate must grade valid — an invalid verdict is a correctness
    bug, not a perf datum. CPU fallback honest: `host_cpus`/`devices`
    ride the record so a 2-core fallback number is never read as the
    TPU figure."""
    import shutil
    import tempfile

    import jax

    from maelstrom_tpu import core

    if mults is None:
        mults = [int(x) for x in os.environ.get(
            "BENCH_STREAM_MULTS", "1,4,16").split(",") if x.strip()]
    base = float(os.environ.get("BENCH_STREAM_RATE", 50.0))
    tl = float(os.environ.get("BENCH_STREAM_TIME_LIMIT", 10.0))
    conc = int(os.environ.get("BENCH_STREAM_CONC", 16))
    rows = []
    root = tempfile.mkdtemp(prefix="bench-stream-")
    try:
        for m in mults:
            rate = base * m
            t0 = time.perf_counter()
            res = core.run(dict(
                store_root=root, seed=11, workload="kafka",
                node="tpu:kafka", node_count=5, concurrency=conc,
                rate=rate, time_limit=tl, journal_rows=False,
                kafka_groups=2, continuous=True, timeout_ms=1000,
                audit=False))
            dt = time.perf_counter() - t0
            w = res["workload"]
            lag = w.get("checker-lag") or {}
            rows.append({
                "rate_mult": m, "offered_rate": rate,
                "wall_s": round(dt, 3),
                "ops": res["stats"]["count"],
                "ops_per_sec": round(res["stats"]["count"] / dt, 1),
                "msgs_per_sec": round(
                    res["net"]["all"]["recv-count"] / dt, 1),
                "acked_sends": w.get("acked-sends"),
                "windows": lag.get("windows"),
                "max_lag_rounds": lag.get("max-lag-rounds"),
                "valid": res["valid"] is True,
                "predicted": predicted_for_test(
                    dict(workload="kafka", node="tpu:kafka",
                         node_count=5, concurrency=conc,
                         time_limit=tl, kafka_groups=2),
                    dt, msgs=res["net"]["all"]["recv-count"]),
            })
            print(f"bench[stream x{m}]: {rows[-1]['ops_per_sec']:.0f} "
                  f"ops/s, {rows[-1]['msgs_per_sec']:.0f} msgs/s, "
                  f"max lag {rows[-1]['max_lag_rounds']} rounds",
                  file=sys.stderr)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {
        "rates": rows,
        "base_rate": base, "time_limit_s": tl, "concurrency": conc,
        "host_cpus": os.cpu_count(),
        "devices": jax.device_count(),
        "valid": all(r["valid"] for r in rows),
    }


def _main_stream():
    """`BENCH_MODE=stream`: the open-world stream record as its own
    artifact, headline `value` = sustained msgs/sec at the highest
    offered rate (same JSON-line contract as the other modes)."""
    stream = bench_stream_record()
    top = max(stream["rates"], key=lambda r: r["rate_mult"])
    record = {
        "metric": "stream_kafka_msgs_per_sec",
        "value": top["msgs_per_sec"],
        "unit": "msgs/sec",
        "vs_baseline": None,
        "rate_mult": top["rate_mult"],
        "max_lag_rounds": top["max_lag_rounds"],
        **stream,
        **_fallback_meta(),
    }
    print(json.dumps(record))
    if not stream["valid"]:
        sys.exit(1)


def _session_pass_micro(F, conc, waves=200):
    """The per-wave session pass in isolation (ISSUE 17): F shells,
    each holding `conc` in-flight RPCs, answering the per-wave queries
    every dispatch loop asks — scan bound (min_deadline), timeout
    expiry (take_expired, nothing due), requeue check — for `waves`
    waves. The coroutine backend pays F Python scans over its pending
    dicts per wave; the columnar table pays ONE vectorized
    `encode_wave` reduction and F O(1) cache reads. This is exactly
    the code the PR moved, measured with the production backends; the
    end-to-end `host_wall_per_wave` column dilutes it with the (shared,
    unchanged) generator-feed pass, so the micro row is where the
    table's win is read directly."""
    from maelstrom_tpu.runner.sessions import (ColumnarSessions,
                                               CoroutineSessions)

    def populate(register):
        mid = 0
        for i in range(F):
            for c in range(conc):
                register(i, mid, c, {"f": "w", "m": mid}, c % 5,
                         10 ** 6 + (mid % 97))
                mid += 1

    cor = [CoroutineSessions() for _ in range(F)]
    populate(lambda i, *a: cor[i].register(*a))
    t0 = time.perf_counter()
    for w in range(waves):
        for s in cor:
            s.min_deadline()
            s.take_expired(w)
            s.has_requeue()
    cor_s = time.perf_counter() - t0

    tab = ColumnarSessions(F, conc)
    views = [tab.view(i) for i in range(F)]
    populate(lambda i, *a: views[i].register(*a))
    t1 = time.perf_counter()
    for w in range(waves):
        tab.encode_wave()
        for v in views:
            v.min_deadline()
            v.take_expired(w)
            v.has_requeue()
    col_s = time.perf_counter() - t1
    return {"fleet": F, "concurrency": conc, "waves": waves,
            "coroutine_us_per_wave": round(1e6 * cor_s / waves, 2),
            "columnar_us_per_wave": round(1e6 * col_s / waves, 2),
            "speedup": round(cor_s / col_s, 2) if col_s else None}


def bench_fleet_stream_record(sizes=None, mults=None) -> dict:
    """Million-session open-world fleets (ISSUE 12, doc/perf.md
    "vectorized host driver"): `--fleet N --continuous` driven END TO
    END through the production entry point — N independent streaming
    kafka clusters (consumer groups, sched-inject windows, per-cluster
    windowed grading) in one vmapped compiled scan — at fleet sizes
    1/8/64 x offered rates 1x/4x. Three numbers per point:

      - sustained AGGREGATE client-ops/s: completed client ops summed
        over the whole fleet per wall second (the fleet lever applied
        to the open-world stream);
      - host polls per cluster: the driver's poll passes (generator
        scheduling + pending scans + columnar encode, one per wave —
        `host-polls` in the results block) divided by fleet size. The
        fleet=1 point IS the sequential-continuous baseline, so
        `poll_amortization` = polls-per-cluster(1) / polls-per-cluster
        (N) is the measured O(waves)-not-O(clusters) claim: every
        cluster advances the same virtual duration, so per-cluster and
        per-cluster-round ratios coincide. Acceptance: >= 8x at the
        largest recorded fleet (a counter ratio — real even on a
        2-core CPU box, unlike throughput ratios);
      - max checker-lag (rounds the scan head led the slowest
        cluster's windowed grader): bounded lag = the per-cluster
        stream graders keep up while the whole fleet runs.

    Plus, per ISSUE 17: `host_wall_per_wave` (mean host seconds per
    poll pass) per point, with every point run under `--sessions
    columnar` and fleets >= BENCH_FLEET_STREAM_COMPARE_MIN (default
    64) also under the legacy coroutine path — `host_wall_flatness`
    is the columnar max/min ratio over fleets >= 8 (acceptance: <= 2x)
    and `session_speedup` the per-point coroutine/columnar wall
    ratio.

    Every point must grade valid. CPU fallback honest: `host_cpus` /
    `devices` ride the record so a fallback aggregate is never read as
    the TPU figure (the throughput column needs real parallel
    hardware; the poll-amortization column does not)."""
    import shutil
    import tempfile

    import jax

    from maelstrom_tpu import core

    if sizes is None:
        sizes = [int(x) for x in os.environ.get(
            "BENCH_FLEET_STREAM_SIZES", "1,8,64").split(",")
            if x.strip()]
    if mults is None:
        mults = [int(x) for x in os.environ.get(
            "BENCH_FLEET_STREAM_MULTS", "1,4").split(",") if x.strip()]
    base = float(os.environ.get("BENCH_FLEET_STREAM_RATE", 16.0))
    tl = float(os.environ.get("BENCH_FLEET_STREAM_TIME_LIMIT", 1.5))
    conc = int(os.environ.get("BENCH_FLEET_STREAM_CONC", 8))
    # the columnar-vs-coroutine session comparison (ISSUE 17): every
    # point runs columnar; fleets >= this floor ALSO run the legacy
    # coroutine path so host_wall_per_wave shows the measured win
    cmp_min = int(os.environ.get("BENCH_FLEET_STREAM_COMPARE_MIN", 64))
    rows = []
    root = tempfile.mkdtemp(prefix="bench-fleet-stream-")
    try:
        for F in sizes:
            for m in mults:
                modes = ["columnar"]
                if F > 1 and F >= cmp_min:
                    modes.append("coroutine")
                for mode in modes:
                    rate = base * m
                    t0 = time.perf_counter()
                    res = core.run(dict(
                        store_root=root, seed=11, workload="kafka",
                        node="tpu:kafka", node_count=5,
                        concurrency=conc,
                        rate=rate, time_limit=tl, journal_rows=False,
                        kafka_groups=2, continuous=True,
                        timeout_ms=1000,
                        recovery_s=0.5, fleet=F, sessions=mode,
                        # keep the per-cluster windowed graders on at
                        # every fleet size (cluster_opts defaults them
                        # off past 16 clusters to bound the thread
                        # pool)
                        check_workers=1, audit=False))
                    dt = time.perf_counter() - t0
                    # the gate is the kafka stream verdict + the net
                    # invariants per cluster; the generic stats smell
                    # rule (every op class needs >= 1 ok) legitimately
                    # trips on short windows when a cluster's only
                    # commit landed during group formation and was
                    # correctly fenced ("rebalanced" is a definite
                    # fail) — recorded as strict_valid, not gated
                    if F > 1:
                        ops = sum(c["stats"]["count"]
                                  for c in res["clusters"])
                        polls = res.get("host-polls", 0)
                        wall_wave = res.get("host-wall-per-wave")
                        lag = res.get("max-checker-lag-rounds")
                        rounds = max(res["final-rounds"])
                        ok = all(c["workload"]["valid"] is True
                                 and c["net"]["valid"] is True
                                 for c in res["clusters"])
                    else:
                        ops = res["stats"]["count"]
                        polls = res["net"].get("host-polls", 0)
                        wall_wave = res["net"].get("host-wall-per-wave")
                        lag = (res["workload"].get("checker-lag")
                               or {}).get("max-lag-rounds")
                        rounds = None
                        ok = (res["workload"]["valid"] is True
                              and res["net"]["valid"] is True)
                    rows.append({
                        "fleet": F, "rate_mult": m,
                        "offered_rate": rate,
                        "sessions": mode,
                        "wall_s": round(dt, 3),
                        "agg_ops": ops,
                        "agg_ops_per_sec": round(ops / dt, 1),
                        "host_polls": polls,
                        "polls_per_cluster": round(polls / F, 2),
                        "host_wall_per_wave": wall_wave,
                        "max_lag_rounds": lag,
                        "max_rounds": rounds,
                        "valid": ok,
                        "strict_valid": res["valid"] is True,
                    })
                    print(
                        f"bench[fleet_stream F={F} x{m} {mode}]: "
                        f"{rows[-1]['agg_ops_per_sec']:.0f} agg ops/s, "
                        f"{polls} polls "
                        f"({rows[-1]['polls_per_cluster']}/cluster, "
                        f"{wall_wave}s/wave), max lag {lag}",
                        file=sys.stderr)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    # poll amortization per (size, rate): fleet-1 polls-per-cluster at
    # the same offered rate over this point's polls-per-cluster —
    # columnar rows only (the coroutine comparison rows measure wall,
    # not the amortization claim)
    col = [r for r in rows if r["sessions"] == "columnar"]
    base_polls = {r["rate_mult"]: r["polls_per_cluster"]
                  for r in col if r["fleet"] == 1}
    for r in rows:
        b = base_polls.get(r["rate_mult"])
        r["poll_amortization"] = (
            round(b / r["polls_per_cluster"], 2)
            if b and r["polls_per_cluster"]
            and r["sessions"] == "columnar" else None)
    top_f = max(r["fleet"] for r in rows)
    top_amort = [r["poll_amortization"] for r in col
                 if r["fleet"] == top_f and r["poll_amortization"]]
    # host-wall-per-wave flatness on the columnar path (the ISSUE 17
    # acceptance: flat within 2x from fleet 8 up) and the measured
    # columnar-over-coroutine win at the compared fleet sizes
    flat_walls = [r["host_wall_per_wave"] for r in col
                  if r["fleet"] >= 8 and r["host_wall_per_wave"]]
    wall_flatness = (round(max(flat_walls) / min(flat_walls), 2)
                     if flat_walls else None)
    speedups = {}
    for r in rows:
        if r["sessions"] != "coroutine" or not r["host_wall_per_wave"]:
            continue
        twin = next((c for c in col
                     if c["fleet"] == r["fleet"]
                     and c["rate_mult"] == r["rate_mult"]
                     and c["host_wall_per_wave"]), None)
        if twin is not None:
            speedups[f"F{r['fleet']}x{r['rate_mult']}"] = round(
                r["host_wall_per_wave"] / twin["host_wall_per_wave"],
                2)
    # "bounded" means the grader keeps up to within a few stream
    # strides of the scan head — comparing against the run's total
    # rounds would be vacuous (lag can never exceed it). The bench
    # runs the default stride, so the bound is a small multiple of it
    # (derived from DEFAULTS so it tracks the real stride), applied to
    # EVERY point including fleet 1.
    stride_rounds = (float(core.DEFAULTS["continuous_window_ms"])
                     / float(core.DEFAULTS.get("ms_per_round") or 1.0))
    lag_bound = int(4 * stride_rounds)
    lag_bounded = all(
        r["max_lag_rounds"] is not None
        and r["max_lag_rounds"] <= lag_bound
        for r in rows)
    # the isolated session-pass micro at each recorded fleet size:
    # the direct coroutine-scan vs columnar-table comparison the
    # end-to-end wall column dilutes with the shared feed pass
    micro = [_session_pass_micro(F, c)
             for F in sizes if F > 1
             for c in (conc, 8 * conc)]
    return {
        "points": rows,
        "base_rate": base, "time_limit_s": tl, "concurrency": conc,
        "top_fleet": top_f,
        "session_pass": micro or None,
        "session_pass_speedup_top": (micro[-1]["speedup"]
                                     if micro else None),
        "poll_amortization_top": (min(top_amort) if top_amort
                                  else None),
        # max/min columnar host_wall_per_wave over fleets >= 8 (the
        # flatness acceptance is <= 2.0) and per-point coroutine-wall /
        # columnar-wall ratios at the compared fleet sizes (> 1 means
        # the columnar table pass beat the coroutine dict scans)
        "host_wall_flatness": wall_flatness,
        "session_speedup": speedups or None,
        "lag_bound_rounds": lag_bound,
        "lag_bounded": lag_bounded,
        "host_cpus": os.cpu_count(),
        "devices": jax.device_count(),
        "valid": all(r["valid"] for r in rows) and lag_bounded,
    }


def _main_fleet_stream():
    """`BENCH_MODE=fleet_stream`: the open-world fleet record as its
    own artifact — headline `value` = sustained aggregate client-ops/s
    at the largest fleet x highest rate, `vs_baseline` = the measured
    host-poll amortization (fleet-1 polls-per-cluster over the largest
    fleet's, >= 8x acceptance when fleet 1 and >= 8 are both
    recorded). Exits nonzero when a point graded invalid, checker lag
    was unbounded, or the amortization missed the floor."""
    rec = bench_fleet_stream_record()
    top = max((r for r in rec["points"]
               if r["sessions"] == "columnar"),
              key=lambda r: (r["fleet"], r["rate_mult"]))
    record = {
        "metric": "fleet_stream_agg_client_ops_per_sec",
        "value": top["agg_ops_per_sec"],
        "unit": "client-ops/sec",
        "vs_baseline": rec["poll_amortization_top"],
        "fleet": top["fleet"],
        "rate_mult": top["rate_mult"],
        "max_lag_rounds": top["max_lag_rounds"],
        **rec,
        **_fallback_meta(),
    }
    print(json.dumps(record))
    amort = rec["poll_amortization_top"]
    amort_bad = (rec["top_fleet"] >= 8 and amort is not None
                 and amort < 8.0)
    if not rec["valid"] or amort_bad:
        sys.exit(1)


def bench_compartment_record(proxies=None) -> dict:
    """Compartmentalized consensus scaling (doc/compartment.md):
    lin-kv client-ops/s vs PROXY count at fixed leader and acceptor
    capacity — the paper's headline claim (arxiv 2012.15762) driven END
    TO END through `core.run` on `--node tpu:compartment`.

    Every sweep point shares one leader budget (inbox + in-flight
    table), one 2x2 acceptor grid, and one replica pair; only the
    stateless proxy tier scales. Offered load is held well above the
    P=1 tier's capacity, so the measured ok-throughput IS the tier's
    saturation capacity: excess commands shed definitely (error 11,
    visible backpressure) and the linearizable verdict must stay valid
    at every point — an invalid verdict is a correctness bug, not a
    perf datum.

    The headline `ops_per_vsec` is VIRTUAL throughput (completed ok ops
    per simulated second): per-node inbox/outbox budgets model the
    NIC/CPU limits the paper's compartments divide, and virtual
    throughput is what scales with P regardless of host speed. Wall
    numbers ride along; `host_cpus`/`devices` keep a CPU-fallback run
    honest."""
    import shutil
    import tempfile

    import jax

    from maelstrom_tpu import core

    if proxies is None:
        proxies = [int(x) for x in os.environ.get(
            "BENCH_COMPARTMENT_PROXIES", "1,2,4,8").split(",")
            if x.strip()]
    rate = float(os.environ.get("BENCH_COMPARTMENT_RATE", 8000.0))
    tl = float(os.environ.get("BENCH_COMPARTMENT_TIME_LIMIT", 2.0))
    conc = int(os.environ.get("BENCH_COMPARTMENT_CONC", 96))
    rows = []
    root = tempfile.mkdtemp(prefix="bench-compartment-")
    try:
        for p in proxies:
            t0 = time.perf_counter()
            res = core.run(dict(
                store_root=root, seed=11, workload="lin-kv",
                node="tpu:compartment",
                roles=f"proxies={p},acceptors=2x2,replicas=2",
                concurrency=conc, rate=rate, time_limit=tl,
                journal_rows=False, audit=False,
                # FIXED leader/acceptor capacity across the sweep: the
                # sequencer's ingest and table budget never change —
                # only the proxy tier scales
                leader_slots=128, proxy_slots=8, compartment_inbox=16,
                kv_keys=1024, timeout_ms=20000))
            dt = time.perf_counter() - t0
            ok = res["stats"]["ok-count"]
            rows.append({
                "proxies": p,
                "ok_ops": ok,
                "ops_per_vsec": round(ok / tl, 1),
                "wall_s": round(dt, 3),
                "ops_per_wall_sec": round(ok / dt, 1),
                "predicted": predicted_for_test(
                    dict(workload="lin-kv", node="tpu:compartment",
                         roles=f"proxies={p},acceptors=2x2,replicas=2",
                         concurrency=conc, time_limit=tl,
                         leader_slots=128, proxy_slots=8,
                         compartment_inbox=16, kv_keys=1024),
                    dt, msgs=res["net"]["all"]["recv-count"]),
                # definite fails: leader backpressure sheds (error 11)
                # PLUS ordinary lin-kv cas-mismatch/absent-key errors —
                # the stats checker doesn't split by code, so this is
                # labeled for what it is
                "failed_ops": res["stats"]["fail-count"],
                "valid": res["valid"] is True,
            })
            print(f"bench[compartment P={p}]: "
                  f"{rows[-1]['ops_per_vsec']:.0f} client-ops/vsec "
                  f"({ok} ok, {rows[-1]['failed_ops']} failed, "
                  f"{dt:.1f}s wall), valid={rows[-1]['valid']}",
                  file=sys.stderr)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    by_p = {r["proxies"]: r for r in rows}
    scaling = None
    if 1 in by_p and 4 in by_p and by_p[1]["ops_per_vsec"]:
        scaling = round(by_p[4]["ops_per_vsec"]
                        / by_p[1]["ops_per_vsec"], 2)
    return {
        "proxies": rows,
        "scaling_1_to_4": scaling,
        "offered_rate": rate, "time_limit_s": tl, "concurrency": conc,
        "host_cpus": os.cpu_count(),
        "devices": jax.device_count(),
        "valid": all(r["valid"] for r in rows),
    }


def _main_compartment():
    """`BENCH_MODE=compartment`: the proxy-scaling record as its own
    artifact, headline `value` = client-ops/vsec at the largest proxy
    count (same JSON-line contract as the other modes). Exits nonzero
    when a sweep point graded invalid or the 1->4 proxy scaling fell
    under the 2x acceptance floor."""
    rec = bench_compartment_record()
    top = max(rec["proxies"], key=lambda r: r["proxies"])
    record = {
        "metric": "compartment_client_ops_per_vsec",
        "value": top["ops_per_vsec"],
        "unit": "client-ops/vsec",
        "vs_baseline": None,
        **rec,
        **_fallback_meta(),
    }
    print(json.dumps(record))
    # the 2x acceptance gate needs both anchor points; a custom
    # BENCH_COMPARTMENT_PROXIES sweep without P=1/P=4 only gates
    # validity
    bad_scaling = (rec["scaling_1_to_4"] is not None
                   and rec["scaling_1_to_4"] < 2.0)
    if not rec["valid"] or bad_scaling:
        sys.exit(1)


def bench_failover_record() -> dict:
    """Leader failover under forced sequencer kills (doc/compartment.md
    "leader election"): `--nemesis-targets kill=sequencer` repeatedly
    kills the LIVE elected leader at the PR 9 acceptance shape
    (leader_slots=128 / inbox 16, 2x2 grid, 2 replicas) with a
    3-candidate sequencer tier, and the record reports

      - mean/max rounds from candidacy to a won election
        (`rounds_to_leader`, off the device election counters),
      - completed failovers (must reach the forced-kill count),
      - client-ops/s BEFORE / DURING / AFTER the kill windows (virtual
        throughput segmented by the history's start-kill/stop-kill
        ops — the availability dip made a number),
      - the availability block's longest no-ok gap and dip count.

    Gates: every run must grade linearizable and complete >= 2
    failovers — a failover bench that lost data or never failed over
    measured nothing."""
    import shutil
    import tempfile

    import jax

    from maelstrom_tpu import core

    rate = float(os.environ.get("BENCH_FAILOVER_RATE", 200.0))
    tl = float(os.environ.get("BENCH_FAILOVER_TIME_LIMIT", 6.0))
    interval = float(os.environ.get("BENCH_FAILOVER_INTERVAL", 0.7))
    root = tempfile.mkdtemp(prefix="bench-failover-")
    try:
        t0 = time.perf_counter()
        res = core.run(dict(
            store_root=root, seed=11, workload="lin-kv",
            node="tpu:compartment",
            roles="sequencers=3,proxies=4,acceptors=2x2,replicas=2",
            concurrency=48, rate=rate, time_limit=tl,
            journal_rows=False, audit=False,
            leader_slots=128, proxy_slots=8, compartment_inbox=16,
            kv_keys=1024, timeout_ms=400,
            nemesis={"kill"}, nemesis_interval=interval,
            nemesis_targets="kill=sequencer", recovery_s=2))
        wall = time.perf_counter() - t0
        ms_pr = 1.0
        ns_pr = ms_pr * 1e6
        # segment ok completions by the kill windows
        kills, heals, oks = [], [], []
        with open(os.path.join(root, "latest", "history.jsonl")) as f:
            for ln in f:
                o = json.loads(ln)
                if o.get("process") == "nemesis" \
                        and o.get("type") == "invoke":
                    if o.get("f") == "start-kill":
                        kills.append(o["time"] / ns_pr)
                    elif o.get("f") == "stop-kill":
                        heals.append(o["time"] / ns_pr)
                elif o.get("type") == "ok":
                    oks.append(o["time"] / ns_pr)
        end_r = tl * 1000.0 / ms_pr
        first_kill = min(kills) if kills else float("inf")

        def window_close(k):
            # the heal that closes THIS kill window; a kill the run
            # ended inside (no later stop-kill) closes at run end, so
            # windows never go negative
            return min((h for h in heals if h >= k), default=end_r)

        # where the LAST kill window closed — not the final generator's
        # trailing stop-kill at run end
        last_heal = max((window_close(k) for k in kills), default=0.0)
        in_window = sum(1 for t in oks
                        for k in kills if k <= t <= window_close(k))
        windows_r = sum(window_close(k) - k for k in kills)
        before = sum(1 for t in oks if t < first_kill)
        after = sum(1 for t in oks if t > last_heal)
        seg = {
            "before": round(before / max(first_kill / 1000.0, 1e-9), 1),
            "during": round(in_window / max(windows_r / 1000.0, 1e-9),
                            1),
            "after": round(after / max((end_r - last_heal) / 1000.0,
                                       1e-9), 1),
        }
        avail = res.get("availability", {})
        elect = avail.get("election", {})
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {
        "failovers": elect.get("failovers", 0),
        "forced_kills": len(kills),
        "rounds_to_leader": elect.get("rounds-to-leader"),
        "client_ops_per_vsec": seg,
        "longest_ok_gap_rounds": avail.get("longest-ok-gap-rounds"),
        "dip_count": avail.get("dip-count"),
        "dip_threshold_rounds": avail.get("dip-threshold-rounds"),
        # the client-side leader lease (doc/compartment.md "client
        # lease") defaults ON at 2x the election timeout: r01 predates
        # it (longest gap ~ the 400-round RPC timeout); with it the gap
        # tracks lease + election (r02: 419 -> 156 rounds, dips 4 -> 0)
        "leader_lease_rounds":
            2 * core.DEFAULTS["election_timeout_rounds"],
        "offered_rate": rate, "time_limit_s": tl,
        "nemesis_interval_s": interval,
        "wall_s": round(wall, 3),
        "host_cpus": os.cpu_count(),
        "devices": jax.device_count(),
        "valid": res["valid"] is True,
    }


def _main_failover():
    """`BENCH_MODE=failover`: the leader-failover record as its own
    artifact, headline `value` = max rounds-to-new-leader (same
    JSON-line contract as the other modes). Exits nonzero when the run
    graded invalid or fewer than 2 failovers completed."""
    rec = bench_failover_record()
    rtl = rec.get("rounds_to_leader") or {}
    record = {
        "metric": "failover_rounds_to_new_leader_max",
        "value": rtl.get("max"),
        "unit": "rounds",
        "vs_baseline": None,
        **rec,
        **_fallback_meta(),
    }
    print(json.dumps(record))
    if not rec["valid"] or rec["failovers"] < 2:
        sys.exit(1)


def bench_ordering_record() -> dict:
    """The ordering-layer matrix made a number (doc/ordering.md):
    lin-kv — the SAME applier — driven end to end over each ordering
    engine (`--ordering raft|compartment|batched`) at EQUAL node count
    (5 nodes: raft's default quintet, the compartment's minimal
    1+1+1x2+1 tier split, a 5-node broadcast cohort), reporting
    client-ops per VIRTUAL second per engine. Every point must grade
    linearizable — the matrix's whole claim is that the stock checker
    vouches for every combination. Virtual throughput is the
    engine-economics number (messages/slots per command under equal
    per-node budgets); wall seconds ride along for the host-speed
    caveat."""
    import shutil
    import tempfile

    import jax

    from maelstrom_tpu import core

    rate = float(os.environ.get("BENCH_ORDERING_RATE", 2000.0))
    tl = float(os.environ.get("BENCH_ORDERING_TIME_LIMIT", 2.0))
    conc = int(os.environ.get("BENCH_ORDERING_CONC", 32))
    engines = [e for e in os.environ.get(
        "BENCH_ORDERING_ENGINES", "raft,compartment,batched").split(",")
        if e.strip()]
    rows = []
    root = tempfile.mkdtemp(prefix="bench-ordering-")
    try:
        for eng in engines:
            opts = dict(
                store_root=root, seed=11, workload="lin-kv",
                ordering=eng, concurrency=conc, rate=rate,
                time_limit=tl, journal_rows=False, audit=False,
                timeout_ms=20000, kv_keys=1024)
            if eng == "compartment":
                # 5 nodes, matching the other engines' cohort
                opts["roles"] = "proxies=1,acceptors=1x2,replicas=1"
            else:
                opts["node_count"] = 5
            t0 = time.perf_counter()
            res = core.run(opts)
            dt = time.perf_counter() - t0
            ok = res["stats"]["ok-count"]
            rows.append({
                "engine": eng,
                "ok_ops": ok,
                "ops_per_vsec": round(ok / tl, 1),
                "wall_s": round(dt, 3),
                "ops_per_wall_sec": round(ok / dt, 1),
                "failed_ops": res["stats"]["fail-count"],
                "valid": (res.get("workload") or {}).get("valid")
                is True,
                "predicted": predicted_for_test(
                    opts, dt, msgs=res["net"]["all"]["recv-count"]),
            })
            print(f"bench[ordering {eng}]: "
                  f"{rows[-1]['ops_per_vsec']:.0f} client-ops/vsec "
                  f"({ok} ok, {dt:.1f}s wall), "
                  f"valid={rows[-1]['valid']}", file=sys.stderr)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {
        "engines": rows,
        "applier": "lin-kv",
        "node_count": 5,
        "offered_rate": rate, "time_limit_s": tl, "concurrency": conc,
        "host_cpus": os.cpu_count(),
        "devices": jax.device_count(),
        "valid": all(r["valid"] for r in rows),
    }


def _main_ordering():
    """`BENCH_MODE=ordering`: the per-engine record as its own
    artifact, headline `value` = the fastest engine's client-ops/vsec
    (same JSON-line contract as the other modes). Exits nonzero when
    any engine's run graded invalid."""
    rec = bench_ordering_record()
    top = max(rec["engines"], key=lambda r: r["ops_per_vsec"])
    record = {
        "metric": "ordering_client_ops_per_vsec",
        "value": top["ops_per_vsec"],
        "unit": "client-ops/vsec",
        "vs_baseline": None,
        "fastest_engine": top["engine"],
        **rec,
        **_fallback_meta(),
    }
    print(json.dumps(record))
    if not rec["valid"]:
        sys.exit(1)


def bench_byzantine_record() -> dict:
    """The conviction contract made a number (doc/faults.md "byzantine
    is a conviction driver"): the SAME compartment cluster (2-candidate
    sequencer tier, tight resend) runs once benign and once under the
    equivocating-sequencer adversary (`--nemesis byzantine`), same
    seed, and the record reports

      - conviction latency: rounds from the first start-byzantine
        invoke to the proxy tier's first-conviction round stamp (the
        device `z_*_rnd` witness field surfaced in the conviction
        evidence),
      - injected-vs-convicted ledger straight from the `byzantine`
        results block,
      - client-ops/s benign vs under attack (the price of running next
        to a liar who gets caught).

    Gates: the byzantine block must grade valid (every injected
    corruption convicted, none spurious) and the benign run must grade
    valid with NO byzantine block — a conviction bench that convicted
    nobody, or convicted the innocent, measured nothing."""
    import shutil
    import tempfile

    import jax

    from maelstrom_tpu import core

    rate = float(os.environ.get("BENCH_BYZ_RATE", 200.0))
    tl = float(os.environ.get("BENCH_BYZ_TIME_LIMIT", 6.0))
    interval = float(os.environ.get("BENCH_BYZ_INTERVAL", 1.5))
    base = dict(
        seed=3, workload="lin-kv", node="tpu:compartment",
        roles="sequencers=2,proxies=2,acceptors=1x2,replicas=1",
        concurrency=16, rate=rate, time_limit=tl,
        journal_rows=False, audit=False,
        compartment_retry=3, kv_keys=1024)
    root = tempfile.mkdtemp(prefix="bench-byzantine-")
    try:
        t0 = time.perf_counter()
        res_b = core.run(dict(base, store_root=root))
        wall_b = time.perf_counter() - t0
        t0 = time.perf_counter()
        res_a = core.run(dict(
            base, store_root=root,
            nemesis={"byzantine"}, nemesis_interval=interval,
            nemesis_targets="byzantine=sequencers",
            byz_attacks="equivocation"))
        wall_a = time.perf_counter() - t0
        ns_pr = 1e6                       # 1 round == 1 virtual ms
        starts = []
        with open(os.path.join(root, "latest", "history.jsonl")) as f:
            for ln in f:
                o = json.loads(ln)
                if o.get("process") == "nemesis" \
                        and o.get("type") == "invoke" \
                        and o.get("f") == "start-byzantine":
                    starts.append(o["time"] / ns_pr)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    blk = res_a.get("byzantine") or {}
    convs = blk.get("convictions") or []
    # the round the proxies FIRST convicted vs the round the nemesis
    # first armed the adversary
    conv_rounds = [c["evidence"]["round"] for c in convs
                   if c.get("evidence", {}).get("round", -1) >= 0]
    latency = (round(min(conv_rounds) - min(starts), 1)
               if conv_rounds and starts else None)
    ok_b = res_b["stats"]["ok-count"]
    ok_a = res_a["stats"]["ok-count"]
    return {
        "attack": "equivocation",
        "attack_windows": len(starts),
        "conviction_latency_rounds": latency,
        "injected": blk.get("injected"),
        "convictions": [
            {"rule": c["rule"], "culprit": c["culprit"],
             "count": c["evidence"].get("count"),
             "witness": c.get("witness")} for c in convs],
        "byzantine_valid": blk.get("valid") is True,
        "client_ops_per_vsec": {
            "benign": round(ok_b / tl, 1),
            "under_attack": round(ok_a / tl, 1),
        },
        "benign_valid": res_b["valid"] is True,
        "benign_convictions": len(
            (res_b.get("byzantine") or {}).get("convictions") or ()),
        "offered_rate": rate, "time_limit_s": tl,
        "nemesis_interval_s": interval,
        "wall_s": {"benign": round(wall_b, 3),
                   "under_attack": round(wall_a, 3)},
        "host_cpus": os.cpu_count(),
        "devices": jax.device_count(),
        "valid": blk.get("valid") is True and res_b["valid"] is True
        and "byzantine" not in res_b,
    }


def _main_byzantine():
    """`BENCH_MODE=byzantine`: the conviction record as its own
    artifact, headline `value` = rounds from injection to the first
    device conviction (same JSON-line contract as the other modes).
    Exits nonzero when the byzantine block graded invalid (an injected
    corruption escaped conviction, or an innocent node was convicted)
    or the benign twin wasn't clean."""
    rec = bench_byzantine_record()
    record = {
        "metric": "byzantine_conviction_latency_rounds",
        "value": rec["conviction_latency_rounds"],
        "unit": "rounds",
        "vs_baseline": None,
        **rec,
        **_fallback_meta(),
    }
    print(json.dumps(record))
    if not rec["valid"]:
        sys.exit(1)


def main():
    from maelstrom_tpu.util import honor_jax_platforms
    honor_jax_platforms()   # JAX_PLATFORMS=cpu smoke runs; no-op unset
    mode = os.environ.get("BENCH_MODE")
    raft = mode == "raft"
    if mode == "fleet":
        metric, unit = "fleet_agg_msgs_per_sec", "msgs/sec"
        fn = _main_fleet
    elif mode == "checker":
        metric = "checker_elle_device_edge_build_ops_per_sec"
        unit = "micro-ops/sec"
        fn = _main_checker
    elif mode == "compartment":
        metric, unit = "compartment_client_ops_per_vsec", "client-ops/vsec"
        fn = _main_compartment
    elif mode == "failover":
        metric, unit = "failover_rounds_to_new_leader_max", "rounds"
        fn = _main_failover
    elif mode == "stream":
        metric, unit = "stream_kafka_msgs_per_sec", "msgs/sec"
        fn = _main_stream
    elif mode == "fleet_stream":
        metric = "fleet_stream_agg_client_ops_per_sec"
        unit = "client-ops/sec"
        fn = _main_fleet_stream
    elif mode == "podmesh":
        metric, unit = "podmesh_agg_msgs_per_sec", "msgs/sec"
        fn = _main_podmesh
    elif mode == "broadcast_batched":
        metric = "broadcast_batched_client_ops_per_sec"
        unit = "client-ops/sec"
        fn = _main_broadcast_batched
    elif mode == "telemetry":
        metric, unit = "telemetry_ring_overhead_pct", "percent"
        fn = _main_telemetry
    elif mode == "ordering":
        metric, unit = "ordering_client_ops_per_vsec", "client-ops/vsec"
        fn = _main_ordering
    elif mode == "byzantine":
        metric, unit = "byzantine_conviction_latency_rounds", "rounds"
        fn = _main_byzantine
    else:
        metric = ("raft_cluster_rounds_per_sec_10k_clusters" if raft
                  else "broadcast_sim_msgs_per_sec_100k_nodes")
        unit = "cluster-rounds/sec" if raft else "msgs/sec"
        fn = bench_raft_clusters if raft else _main_broadcast
    # EVERYTHING that can touch a backend runs inside this guard: a
    # parseable JSON line must be emitted on every path, including an
    # init failure before the benchmark proper starts (the r05 failure
    # class: nonzero exit, no record)
    try:
        if not os.environ.get("JAX_PLATFORMS"):
            # default backend (possibly a tunneled TPU): bound its init
            # with a killable subprocess probe before committing this
            # process to it — a hanging init would otherwise eat the
            # driver's whole timeout budget (BENCH_r05: rc=124)
            probe_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 120))
            if _probe_backend(probe_s) is None:
                _fall_back_to_cpu("backend probe failed or timed out")
        # the probe only guards against a HANGING init; the
        # authoritative platform check is in-process (the flaky tunnel
        # can resolve differently here, and jax silently falls back to
        # cpu on a FAST accelerator failure). However cpu was reached —
        # probe fallback, silent auto-fallback, or an explicit
        # JAX_PLATFORMS=cpu smoke — the full-size accelerator config
        # would grind for hours on it, so the shrunk defaults apply
        # unless the operator pinned BENCH_* sizes (setdefault
        # semantics: explicit env always wins).
        try:
            import jax
            backend = jax.default_backend()
        except Exception as e:
            if not _is_env_error(e):
                raise
            # in-process init died even though the probe passed (or an
            # explicitly pinned platform is down): one CPU pass beats
            # no artifact
            _fall_back_to_cpu(f"in-process backend init failed: {e}")
            backend = "cpu"
        if backend == "cpu" and not os.environ.get("BENCH_FALLBACK"):
            _fall_back_to_cpu("running on the cpu backend")
        return run_with_env_retry(fn, metric=metric, unit=unit)
    except SystemExit:
        raise               # benches exit nonzero AFTER their JSON line
    except Exception as e:
        # a real bug still produces one parseable record before failing
        # — with the full traceback on stderr so the artifact names the
        # guilty line, not just the exception type
        import traceback
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": metric, "value": None, "unit": unit,
            "vs_baseline": None,
            "error": f"{type(e).__name__}: {e}",
            **_fallback_meta()}))
        sys.exit(1)


def _main_broadcast():
    import jax
    import jax.numpy as jnp

    from maelstrom_tpu.net import tpu as T
    from maelstrom_tpu.nodes import get_program
    from maelstrom_tpu.nodes.broadcast import T_BCAST
    from maelstrom_tpu.sim import dealias, make_run_fn, make_sim

    N = int(os.environ.get("BENCH_NODES", 100_000))
    V = int(os.environ.get("BENCH_VALUES", 64))
    # 700 rounds: injections end at round 128 and the deterministic
    # zero-latency grid flood completes before 700 (the run exits nonzero
    # if convergence is ever lost); more rounds only add idle tail
    R = int(os.environ.get("BENCH_ROUNDS", 700))
    # rounds per scan dispatch: long single dispatches (>~60 s device time)
    # are killed by the remote-TPU tunnel, so the scan is chunked
    chunk = int(os.environ.get("BENCH_CHUNK", 100))
    pool_cap = int(os.environ.get("BENCH_POOL", 8192))
    R = max(chunk, (R // chunk) * chunk)   # at least one chunk

    # Eager-resend gossip maximizes per-round message load (pending values
    # retransmit until digest-acked); the efficient send-once protocol is
    # the interactive default. Both converge; this knob only changes how
    # much traffic the network is asked to simulate.
    eager = os.environ.get("BENCH_EAGER", "1") == "1"
    nodes = [f"n{i}" for i in range(N)]
    # one gossip lane per edge: the eager-resend protocol delivers the
    # same total message volume (pending values retransmit every round
    # until digest-acked) over cheaper rounds — measured 2.85M msgs/s vs
    # 1.68M at 4 lanes on a v5e chip
    per_nb = int(os.environ.get("BENCH_GOSSIP", 1))
    program = get_program("broadcast",
                          {"topology": "grid", "max_values": V,
                           "gossip_per_neighbor": per_nb,
                           "latency": {"mean": 0},
                           "eager_resend": eager},
                          nodes)
    cfg = T.NetConfig(n_nodes=N, n_clients=1, pool_cap=pool_cap,
                      inbox_cap=program.inbox_cap, client_cap=0)
    # donated carry (BENCH_DONATE=0 to compare): at 100k nodes the sim
    # tree is hundreds of MB; reusing its buffers across the chunked
    # scan dispatches removes a full-tree alloc+copy per chunk.
    # donation_enabled() keeps it off on the CPU backend (see sim.py)
    from maelstrom_tpu.sim import donation_enabled
    donate = (os.environ.get("BENCH_DONATE", "1") == "1"
              and donation_enabled())
    run_fn = make_run_fn(program, cfg, donate=donate)

    # Injection plan: V broadcast values, one every other round, spread
    # across the grid by a Fibonacci-hash stride.
    rr = np.arange(R)
    inj_round = (rr % 2 == 0) & (rr // 2 < V)
    value = (rr // 2) % V
    dest = (value.astype(np.int64) * 2654435761) % N
    plan = T.Msgs.empty((R, 1)).replace(
        valid=jnp.asarray(inj_round[:, None]),
        src=jnp.full((R, 1), N, T.I32),
        dest=jnp.asarray(dest.astype(np.int32)[:, None]),
        type=jnp.full((R, 1), T_BCAST, T.I32),
        a=jnp.asarray(value.astype(np.int32)[:, None]))
    chunks = jax.tree.map(
        lambda f: f.reshape((R // chunk, chunk) + f.shape[1:]), plan)

    dev = jax.devices()[0]
    print(f"bench: {N} nodes, {V} values, {R} rounds ({chunk}/dispatch), "
          f"pool {pool_cap}, device {dev.device_kind}", file=sys.stderr)

    def timed_runs(program_x, run_fn_x, label):
        """Compile+first run, then a timed run on fresh state. Returns
        (stats, converged, wall_s)."""
        def run(seed):
            # dealias: a donated carry may not contain one buffer twice
            # (skipped when donation is off — it's a full-tree copy
            # inside the timed window, hundreds of MB at 100k nodes)
            sim = make_sim(program_x, cfg, seed=seed)
            if donate:
                sim = dealias(sim)
            for i in range(R // chunk):
                sim, _counts = run_fn_x(
                    sim, jax.tree.map(lambda f: f[i], chunks))
            # device_get forces actual remote completion;
            # block_until_ready alone does not synchronize through the
            # axon tunnel
            assert int(jax.device_get(sim.net.round)) == R
            return sim

        t0 = time.perf_counter()
        run(seed=0)
        print(f"bench{label}: compile+first run "
              f"{time.perf_counter()-t0:.1f}s", file=sys.stderr)
        t0 = time.perf_counter()
        sim2 = run(seed=1)
        dt = time.perf_counter() - t0
        st = T.stats_dict(sim2.net)
        seen = np.asarray(jax.device_get(sim2.nodes["seen"][:, :V]))
        return st, bool(seen.all()), dt

    st, converged, dt = timed_runs(program, run_fn, "")
    msgs = st["recv_all"]
    rate = msgs / dt

    record = {
        "metric": "broadcast_sim_msgs_per_sec_100k_nodes"
        if N == 100_000 else f"broadcast_sim_msgs_per_sec_{N}_nodes",
        "value": round(rate, 1),
        "unit": "msgs/sec",
        "vs_baseline": round(rate / 1e6, 4),
        "nodes": N, "values": V, "rounds": R,
        "wall_s": round(dt, 3),
        "messages_delivered": int(msgs),
        "converged": converged,
        "eager_resend": eager,
        "dropped_overflow": st["dropped_overflow"],
        "donated_carry": donate,
        "predicted": predicted_block(
            program, cfg,
            measured_rounds_per_sec=R / dt,
            msgs_per_round=msgs / R,
            rounds_per_dispatch=chunk),
        **_fallback_meta(),
    }

    # the efficient (send-once-plus-retry) protocol is the interactive
    # default — the number a user actually gets — so IT is the headline
    # `value`; the eager-resend flood stays in the record as the stress
    # figure (`eager_msgs_per_sec`). Both beat the 1M north star.
    if eager and os.environ.get("BENCH_EFFICIENT", "1") == "1":
        program_eff = get_program(
            "broadcast",
            {"topology": "grid", "max_values": V,
             "gossip_per_neighbor": per_nb, "latency": {"mean": 0},
             "eager_resend": False}, nodes)
        st_e, conv_e, dt_e = timed_runs(
            program_eff, make_run_fn(program_eff, cfg, donate=donate),
            "[efficient]")
        record["value"] = round(st_e["recv_all"] / dt_e, 1)
        record["vs_baseline"] = round(st_e["recv_all"] / dt_e / 1e6, 4)
        record["eager_resend"] = False
        record["eager_msgs_per_sec"] = round(rate, 1)
        record["eager_messages_delivered"] = int(msgs)
        record["eager_wall_s"] = round(dt, 3)
        record["messages_delivered"] = int(st_e["recv_all"])
        record["wall_s"] = round(dt_e, 3)
        record["converged"] = conv_e
        record["eager_converged"] = converged
        record["dropped_overflow"] = st_e["dropped_overflow"]
        record["eager_dropped_overflow"] = st["dropped_overflow"]

    # checker-graded run at the same scale: real history, stock
    # BroadcastChecker (the north star's "passing the stock checker")
    graded = None
    if os.environ.get("BENCH_GRADED", "1") == "1":
        from maelstrom_tpu.bench_graded import run_graded
        out_dir = os.environ.get(
            "BENCH_GRADED_DIR",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "artifacts", f"bench-graded-{N}"))
        graded = run_graded(N, V, chunk=chunk, pool_cap=pool_cap,
                            out_dir=out_dir)
        record["graded"] = {k: v for k, v in graded.items()
                            if k != "checker"}
        record["graded"]["stable_latencies_ms"] = \
            graded["checker"]["stable-latencies"]

    # analysis-pipeline throughput (host/numpy only; BENCH_CHECKER=0
    # to skip): register fast path + elle edge build vs their
    # pure-Python baselines on synthetic 1M-op histories
    checker = None
    if os.environ.get("BENCH_CHECKER", "1") == "1":
        checker = bench_checkers_record()
        record["checker"] = checker

    # fleet-execution scaling (--fleet N; BENCH_FLEET=0 to skip):
    # clusters/sec + aggregate msgs/sec at fleet sizes 1/8/64/512, so
    # the campaign-throughput lever lands in the same BENCH_*.json as
    # the per-cluster headline
    fleet = None
    if os.environ.get("BENCH_FLEET", "1") == "1":
        fleet = bench_fleet_record()
        record["fleet"] = fleet

    # batched atomic broadcast (ISSUE 9; BENCH_BATCHED=0 to skip):
    # distilled-batch vs eager-resend client-ops/s at equal node count,
    # so the recapture records old and new metric in one run
    batched = None
    if os.environ.get("BENCH_BATCHED", "1") == "1":
        batched = bench_broadcast_batched_record()
        record["broadcast_batched"] = batched

    print(json.dumps(record))
    # a non-converged, lossy, or checker-failed run is not a valid
    # benchmark: fail loudly (after emitting the JSON record)
    if not record["converged"] or record["dropped_overflow"]:
        sys.exit(1)
    if (record.get("eager_converged") is False
            or record.get("eager_dropped_overflow")):
        sys.exit(1)
    if graded is not None and graded["checker_valid"] is not True:
        sys.exit(1)
    # a checker fast path that disagrees with its baseline is a
    # correctness bug, not a perf datum
    if checker is not None and not checker["valid"]:
        sys.exit(1)
    # a fleet size that fails to converge (or drops messages) is a
    # correctness bug in the vmapped scan, not a perf datum
    if fleet is not None and not fleet["valid"]:
        sys.exit(1)
    # a batched-broadcast side that fails to converge is a protocol
    # bug in the range-gossip node, not a perf datum
    if batched is not None and not batched["valid"]:
        sys.exit(1)


def _main_checker():
    """`BENCH_MODE=checker`: the checker-throughput record as its own
    artifact (run_tpu_recapture.sh step 1f), headline `value` = the
    device edge build's micro-ops/sec at 1M micro-ops, `vs_baseline` =
    its speedup over `_edges_python` — the ISSUE 11 acceptance figure —
    with the register/elle host ratios and the screen decided-fraction
    riding the same record. Exits nonzero when any half mismatches its
    baseline or the screen decides < 90% of the acyclic fixtures."""
    rec = bench_checkers_record()
    dev = (rec["elle"].get("device") or {})
    record = {
        "metric": "checker_elle_device_edge_build_ops_per_sec",
        "value": dev.get("build_ops_per_s"),
        "unit": "micro-ops/sec",
        "vs_baseline": dev.get("speedup"),
        **rec,
        **_fallback_meta(),
    }
    print(json.dumps(record))
    if not rec["valid"]:
        sys.exit(1)


def bench_telemetry_record() -> dict:
    """The flight-recorder overhead record (ISSUE 13,
    doc/observability.md): the SAME chunked broadcast scan (eager
    resend — the message-heaviest round body) run with the device
    metric rings compiled OUT and compiled IN, msgs/s compared. The
    ring is ~20 small int32 ops per round beside the round's sorts and
    scatters, so the acceptance budget is < 5% on the CPU box
    (`BENCH_TEL_MAX_OVERHEAD_PCT` overrides). Each config takes the
    best of `BENCH_TEL_REPS` timed passes (2-core CPU boxes are
    noisy); histories are byte-identical by construction (pinned in
    tests/test_telemetry.py), so only wall time is compared here."""
    import jax
    import jax.numpy as jnp

    from maelstrom_tpu.net import tpu as T
    from maelstrom_tpu.nodes import get_program
    from maelstrom_tpu.nodes.broadcast import T_BCAST
    from maelstrom_tpu.sim import (dealias, donation_enabled,
                                   make_run_fn, make_sim)

    N = int(os.environ.get("BENCH_TEL_NODES", 4096))
    V = int(os.environ.get("BENCH_TEL_VALUES", 64))
    R = int(os.environ.get("BENCH_TEL_ROUNDS", 400))
    chunk = min(int(os.environ.get("BENCH_CHUNK", 100)), R)
    reps = max(int(os.environ.get("BENCH_TEL_REPS", 2)), 1)
    max_overhead = float(os.environ.get("BENCH_TEL_MAX_OVERHEAD_PCT",
                                        5.0))
    R = max(chunk, (R // chunk) * chunk)

    nodes = [f"n{i}" for i in range(N)]
    program = get_program("broadcast",
                          {"topology": "grid", "max_values": V,
                           "gossip_per_neighbor": 1,
                           "latency": {"mean": 0},
                           "eager_resend": True},
                          nodes)
    donate = donation_enabled()

    rr = np.arange(R)
    inj_round = (rr % 2 == 0) & (rr // 2 < V)
    value = (rr // 2) % V
    dest = (value.astype(np.int64) * 2654435761) % N
    plan = T.Msgs.empty((R, 1)).replace(
        valid=jnp.asarray(inj_round[:, None]),
        src=jnp.full((R, 1), N, T.I32),
        dest=jnp.asarray(dest.astype(np.int32)[:, None]),
        type=jnp.full((R, 1), T_BCAST, T.I32),
        a=jnp.asarray(value.astype(np.int32)[:, None]))
    chunks = jax.tree.map(
        lambda f: f.reshape((R // chunk, chunk) + f.shape[1:]), plan)

    def measure(telemetry: bool):
        cfg = T.NetConfig(
            n_nodes=N, n_clients=1, pool_cap=8192,
            inbox_cap=program.inbox_cap, client_cap=0,
            telemetry=telemetry,
            telemetry_roles=((0, N),) if telemetry else ())
        run_fn = make_run_fn(program, cfg, donate=donate)

        def run(seed):
            sim = make_sim(program, cfg, seed=seed)
            if donate:
                sim = dealias(sim)
            for i in range(R // chunk):
                sim, _counts = run_fn(
                    sim, jax.tree.map(lambda f: f[i], chunks))
            assert int(jax.device_get(sim.net.round)) == R
            return sim

        t0 = time.perf_counter()
        run(seed=0)             # compile + first run, untimed
        print(f"bench[telemetry rings={'on' if telemetry else 'off'}]:"
              f" compile+first {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        best, sim = None, None
        for rep in range(reps):
            t0 = time.perf_counter()
            sim = run(seed=1)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        st = T.stats_dict(sim.net)
        seen = np.asarray(jax.device_get(sim.nodes["seen"][:, :V]))
        ring = None
        if telemetry:
            from maelstrom_tpu import telemetry as TM
            ring = TM.ring_dict(jax.device_get(sim.telemetry))
        return st, bool(seen.all()), best, ring

    print(f"bench[telemetry]: {N} nodes, {V} values, {R} rounds "
          f"({chunk}/dispatch), reps {reps}", file=sys.stderr)
    st_off, conv_off, dt_off, _ = measure(False)
    st_on, conv_on, dt_on, ring = measure(True)
    rate_off = st_off["sent_all"] / dt_off
    rate_on = st_on["sent_all"] / dt_on
    overhead = (1.0 - rate_on / rate_off) * 100.0
    rec = {
        "nodes": N, "values": V, "rounds": R,
        "reps_best_of": reps,
        "msgs_per_sec_off": round(rate_off, 1),
        "msgs_per_sec_on": round(rate_on, 1),
        "wall_s_off": round(dt_off, 3),
        "wall_s_on": round(dt_on, 3),
        "overhead_pct": round(overhead, 3),
        "max_overhead_pct": max_overhead,
        "sent_identical": st_off["sent_all"] == st_on["sent_all"],
        "converged": conv_off and conv_on,
        "ring": {k: v for k, v in (ring or {}).items()
                 if isinstance(v, int)},
        "valid": (conv_off and conv_on
                  and st_off["sent_all"] == st_on["sent_all"]
                  and overhead < max_overhead),
    }
    return rec


def _main_telemetry():
    """`BENCH_MODE=telemetry`: the flight-recorder overhead record
    (rings on vs off, same JSON-line contract as the other modes;
    headline `value` = overhead percent, gate < 5%)."""
    rec = bench_telemetry_record()
    record = {
        "metric": "telemetry_ring_overhead_pct",
        "value": rec["overhead_pct"],
        "unit": "percent",
        "vs_baseline": rec["msgs_per_sec_on"] / rec["msgs_per_sec_off"],
        **rec,
        **_fallback_meta(),
    }
    print(json.dumps(record))
    if not rec["valid"]:
        sys.exit(1)


def _main_fleet():
    """`BENCH_MODE=fleet`: the fleet scaling record as its own
    artifact, headline `value` = aggregate msgs/sec at the largest
    fleet size (same JSON-line contract as the other modes)."""
    fleet = bench_fleet_record()
    top = max(fleet["sizes"], key=lambda r: r["fleet"])
    record = {
        "metric": "fleet_agg_msgs_per_sec",
        "value": top["agg_msgs_per_sec"],
        "unit": "msgs/sec",
        "vs_baseline": top["agg_speedup_vs_fleet1"],
        "fleet": top["fleet"],
        "clusters_per_sec": top["clusters_per_sec"],
        **fleet,
        **_fallback_meta(),
    }
    print(json.dumps(record))
    if not fleet["valid"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
