#!/usr/bin/env bash
# Supervisor relaunch loop for preemption-tolerant runs
# (doc/checkpoint.md). Runs a TPU-path test with checkpointing and
# relaunches it with --resume whenever it exits preempted:
#
#   - rc 75 (EXIT_PREEMPTED): the run caught SIGTERM/SIGINT, finished
#     its in-flight compiled stretch, and wrote a final checkpoint.
#   - rc 137 (SIGKILL) with a checkpoint on disk: hard-killed mid-run;
#     resume from the last durable periodic checkpoint.
#
# Set KILL_AFTER_S to have the wrapper itself SIGKILL the child after a
# random 0..KILL_AFTER_S seconds each launch (a shell-only crash soak;
# `python -m maelstrom_tpu.crash_soak` is the checked, bit-identity
# version). Any other exit code ends the loop with that code.
#
# Usage:
#   ./run_crash_soak.sh                      # default lin-kv fault soup
#   ./run_crash_soak.sh --node tpu:kafka -w kafka --time-limit 60 ...
#   KILL_AFTER_S=5 ./run_crash_soak.sh      # randomized SIGKILL soak
set -u

STORE="${STORE:-store}"
MAX_RELAUNCHES="${MAX_RELAUNCHES:-50}"

if [ "$#" -gt 0 ]; then
    ARGS=("$@")
else
    ARGS=(--node tpu:lin-kv -w lin-kv --node-count 5 --rate 10
          --time-limit 30 --nemesis kill,pause,partition,duplicate
          --nemesis-interval 2 --checkpoint-every 1)
fi

RESUME=()
relaunches=0
while :; do
    if [ -n "${KILL_AFTER_S:-}" ]; then
        python -m maelstrom_tpu test "${ARGS[@]}" --store "$STORE" \
            ${RESUME[@]+"${RESUME[@]}"} &
        child=$!
        # kill at a random moment; if the run finishes first, reap it
        sleep_s=$(awk -v max="$KILL_AFTER_S" \
            'BEGIN{srand(); printf "%.2f", rand()*max}')
        (sleep "$sleep_s" && kill -9 "$child" 2>/dev/null) &
        killer=$!
        wait "$child"
        rc=$?
        kill "$killer" 2>/dev/null
        wait "$killer" 2>/dev/null
    else
        python -m maelstrom_tpu test "${ARGS[@]}" --store "$STORE" \
            ${RESUME[@]+"${RESUME[@]}"}
        rc=$?
    fi

    # the run in progress (store/current) is where checkpoints land
    last=$(readlink -f "$STORE/current" 2>/dev/null || true)
    if [ "$rc" -eq 75 ] || [ "$rc" -eq 137 ]; then
        relaunches=$((relaunches + 1))
        if [ "$relaunches" -gt "$MAX_RELAUNCHES" ]; then
            echo "run_crash_soak: gave up after $MAX_RELAUNCHES relaunches" >&2
            exit 1
        fi
        if [ -n "$last" ] && { [ -e "$last/checkpoint.pkl" ] ||
                [ -e "$last/checkpoint.prev.pkl" ]; }; then
            echo "run_crash_soak: rc=$rc, relaunching with --resume $last" \
                 "(relaunch $relaunches)" >&2
            RESUME=(--resume "$last")
        else
            # killed before the first checkpoint of a fresh run: start
            # over (determinism makes the retry equivalent); keep any
            # previous RESUME if one was already in effect
            echo "run_crash_soak: rc=$rc, no new checkpoint —" \
                 "relaunching (relaunch $relaunches)" >&2
        fi
        continue
    fi
    exit "$rc"
done
