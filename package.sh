#!/bin/bash
# Builds a distribution tarball (the counterpart of the reference's
# package.sh: clean, regenerate docs, run the test suite, package).
set -euo pipefail
cd "$(dirname "$0")"

VERSION="${1:-0.2.0}"
OUT="maelstrom-tpu-${VERSION}"

python3 -m maelstrom_tpu doc
python3 -m pytest tests/ -q

rm -rf "dist/$OUT" "dist/$OUT.tar.bz2"
mkdir -p "dist/$OUT"
cp -r maelstrom_tpu demo doc pkg README.md bench.py "dist/$OUT/"
find "dist/$OUT" -name __pycache__ -type d -exec rm -rf {} +
tar -C dist -cjf "dist/$OUT.tar.bz2" "$OUT"
echo "dist/$OUT.tar.bz2"
